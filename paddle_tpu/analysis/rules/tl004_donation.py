"""TL004 — use-after-donate.

``donate_argnums`` lets XLA alias an input buffer into an output: after
the jitted call the donated array is DELETED (or, with the PR 2
compilation-cache bug, silently corrupted) — reading it again is the
donation bug class that manifests as flaky corruption, not a clean
error.  The rule resolves, lexically per scope:

* donating callables — ``g = jax.jit(f, donate_argnums=(0, 2))`` /
  ``jit(f, donate_argnames=...)`` assignments, and defs decorated with
  ``partial(jax.jit, donate_argnums=...)`` —
* their call sites, marking the argument names passed at donated
  positions dead,
* any later load of a dead name before it is rebound.  Loop bodies are
  scanned twice so a donation in iteration N caught by a load at the
  top of iteration N+1 (the canonical un-rebound training loop) is
  reported.

Dotted receivers (``self._opt_state``) participate like plain names.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import core


def _donate_spec(call: ast.Call) -> Optional[Tuple[Set[int], Set[str]]]:
    """(positions, argnames) from a jit-like call, or None if it does
    not donate."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        elif kw.arg == "donate_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return (nums, names) if (nums or names) else None


def _is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and core.tail_name(node.func) in ("jit", "jit_compile")


class _ScopeScanner:
    """Linear dead-name scan of one function (or module) body."""

    def __init__(self, rule, module, donators: Dict[str, Tuple[Set[int],
                                                               Set[str]]],
                 local_funcs):
        self.rule = rule
        self.module = module
        self.donators = dict(donators)
        self.local_funcs = local_funcs
        self.dead: Dict[str, int] = {}        # name -> donation line
        self.findings: List[core.Finding] = []
        self._reported: Set[Tuple[int, str]] = set()

    # -- helpers --------------------------------------------------------
    def _param_names(self, fname: str) -> List[str]:
        fn = self.local_funcs.get(fname)
        if fn is None:
            return []
        return [a.arg for a in fn.args.posonlyargs + fn.args.args]

    def _donated_args(self, call: ast.Call, spec) -> List[ast.AST]:
        nums, argnames = spec
        out = []
        for i, a in enumerate(call.args):
            if i in nums:
                out.append(a)
        if argnames:
            callee = core.tail_name(call.func)
            positional = self._param_names(callee)
            for i, a in enumerate(call.args):
                if i < len(positional) and positional[i] in argnames:
                    out.append(a)
            for kw in call.keywords:
                if kw.arg in argnames:
                    out.append(kw.value)
        return out

    def _flag(self, name: str, node: ast.AST):
        key = (getattr(node, "lineno", 0), name)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(self.rule.finding(
            self.module, node,
            f"`{name}` is read after being donated on line "
            f"{self.dead[name]} — the buffer no longer holds the value",
            hint="rebind the name to the call's result (or drop "
                 "donation for buffers you must keep)"))

    # -- event emission -------------------------------------------------
    def _expr_events(self, node: ast.AST):
        """Process loads and donations inside an expression."""
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(sub, "ctx", None), ast.Load):
                name = core.dotted_name(sub)
                if name in self.dead:
                    # attribute loads of a dead dotted name, and plain
                    # names, both count; skip sub-chains of longer names
                    self._flag(name, sub)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee = core.dotted_name(sub.func)
                spec = self.donators.get(callee)
                if spec is None and _is_jit_call(sub):
                    continue      # building the wrapper donates nothing
                if spec is not None:
                    for a in self._donated_args(sub, spec):
                        nm = core.dotted_name(a)
                        if nm:
                            self.dead[nm] = getattr(sub, "lineno", 0)

    def _store(self, target: ast.AST):
        for sub in ast.walk(target):
            if isinstance(sub, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(sub, "ctx", None), ast.Store):
                self.dead.pop(core.dotted_name(sub), None)

    # -- statement walk -------------------------------------------------
    def run(self, body: List[ast.stmt]):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.Assign):
            # donating-callable binding? g = jax.jit(f, donate_argnums=..)
            if _is_jit_call(stmt.value):
                spec = _donate_spec(stmt.value)
                if spec and len(stmt.targets) == 1:
                    nm = core.dotted_name(stmt.targets[0])
                    if nm:
                        self.donators[nm] = spec
            self._expr_events(stmt.value)
            for t in stmt.targets:
                self._store(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._expr_events(stmt.value)
            self._store(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr_events(stmt.iter)
            self._store(stmt.target)
            # two passes over the body: the second catches iteration-N+1
            # loads of names donated (and never rebound) in iteration N
            self.run(stmt.body)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._expr_events(stmt.test)
            self.run(stmt.body)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self._expr_events(stmt.test)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr_events(item.context_expr)
                if item.optional_vars is not None:
                    self._store(item.optional_vars)
            self.run(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.run(stmt.body)
            for h in stmt.handlers:
                self.run(h.body)
            self.run(stmt.orelse)
            self.run(stmt.finalbody)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._expr_events(stmt.value)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass     # nested scopes are scanned separately
        else:
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self._expr_events(sub)


@core.register
class DonationRule(core.Rule):
    id = "TL004"
    name = "use-after-donate"
    severity = "error"
    doc = ("a name passed at a donate_argnums/donate_argnames position "
           "of a jitted call is read again before being rebound")
    hint = ("rebind the name to the call's result (or drop donation "
            "for buffers you must keep)")

    def _decorated_donators(self, module):
        out: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for name, fn in module.functions.items():
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    target = dec
                    if core.tail_name(dec.func) == "partial" and dec.args \
                            and core.tail_name(dec.args[0]) in ("jit",
                                                                "jit_compile"):
                        target = dec
                    elif core.tail_name(dec.func) not in ("jit",
                                                          "jit_compile"):
                        continue
                    spec = _donate_spec(target)
                    if spec:
                        out[name] = spec
        return out

    def check(self, module):
        decorated = self._decorated_donators(module)
        scopes = [module.tree] + list(module.functions.values())
        for scope in scopes:
            body = scope.body if hasattr(scope, "body") else []
            sc = _ScopeScanner(self, module, decorated, module.functions)
            sc.run(body)
            yield from sc.findings
