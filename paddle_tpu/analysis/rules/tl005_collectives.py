"""TL005 — collective axis-name consistency.

``lax.psum(x, "pm")`` inside a shard_map whose mesh has axes
``("dp", "mp")`` fails at trace time at best and, with partial-manual
meshes, silently reduces over the wrong group at worst.  The project
convention (``parallel/topology.py``: DP_AXIS/MP_AXIS/PP_AXIS/
SEP_AXIS/SHARDING_AXIS, threaded through ``parallel/manual.py``) is to
never hard-code an axis string at a collective call site.

``prepare`` builds the project-wide axis vocabulary from every scanned
file: ``*_AXIS = "..."`` module constants plus ``axis_names=(...)``
mesh arguments.  A collective called with a string LITERAL not in that
vocabulary is flagged as drift/typo; known literals pass (they can be
deliberate single-file conventions).
"""

from __future__ import annotations

import ast

from .. import core

_COLLECTIVES = {"psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle",
                "all_gather", "all_to_all", "psum_scatter", "axis_index",
                "axis_size"}


@core.register
class CollectiveAxisRule(core.Rule):
    id = "TL005"
    name = "collective-axis-drift"
    severity = "warning"
    doc = ("a lax collective is called with a string-literal axis name "
           "that matches no *_AXIS constant or mesh axis_names entry "
           "anywhere in the scanned tree")
    hint = ("use the topology constants (parallel/topology.py MP_AXIS "
            "et al.) — or add the new axis to the mesh that names it")

    def __init__(self):
        self.vocab = set()

    def prepare(self, modules):
        self.vocab = set()
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id.endswith("_AXIS") \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    self.vocab.add(node.value.value)
                elif isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if kw.arg == "axis_names" and isinstance(
                                kw.value, (ast.Tuple, ast.List)):
                            for e in kw.value.elts:
                                if isinstance(e, ast.Constant) \
                                        and isinstance(e.value, str):
                                    self.vocab.add(e.value)

    def _axis_literals(self, call: ast.Call):
        cands = []
        if len(call.args) >= 2:
            cands.append(call.args[1])
        elif call.args and core.tail_name(call.func) in ("axis_index",
                                                         "axis_size"):
            cands.append(call.args[0])
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                cands.append(kw.value)
        out = []
        for c in cands:
            elts = c.elts if isinstance(c, (ast.Tuple, ast.List)) else [c]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append((e, e.value))
        return out

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if core.tail_name(node.func) not in _COLLECTIVES:
                continue
            for expr, value in self._axis_literals(node):
                if value not in self.vocab:
                    yield self.finding(
                        module, expr,
                        f"collective `{core.tail_name(node.func)}` uses "
                        f"axis name {value!r} which matches no *_AXIS "
                        f"constant or mesh axis_names in the scanned "
                        f"tree")
