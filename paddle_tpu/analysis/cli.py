"""``python -m paddle_tpu.analysis`` — the tracelint CLI.

Modes:

* file/dir:  ``python -m paddle_tpu.analysis paddle_tpu/ bench.py``
  (no paths: the repo's lint surface — paddle_tpu/, bench.py, tools/)
* diff:      ``python -m paddle_tpu.analysis --diff HEAD~1`` — only
  files changed versus the git ref
* output:    human (default) or ``--json``
  (``{"version": 1, "findings": [...], "counts": {...}}``)

When committed ledgers exist (TRACELINT.md for TL rules, KERNELLINT.md
for KL rules; override: ``--baseline PATH``, opt out:
``--no-baseline``) the exit code reports the RATCHET against their
union, not raw findings: 0 at-or-below baseline, 2 above.  Without a
baseline, any finding exits 1.  ``--select`` accepts prefixes: the
kernellint lane is ``--select KL``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from . import core

DEFAULT_LINT_SURFACE = ("paddle_tpu", "bench.py", "tools")


def default_paths() -> List[str]:
    root = core.repo_root()
    return [os.path.join(root, p) for p in DEFAULT_LINT_SURFACE
            if os.path.exists(os.path.join(root, p))]


def _diff_paths(ref: str) -> List[str]:
    root = core.repo_root()
    proc = subprocess.run(
        ["git", "-C", root, "diff", "--name-only", ref, "--", "*.py"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise SystemExit(f"tracelint: git diff {ref} failed: "
                         f"{proc.stderr.strip()}")
    out = []
    for rel in proc.stdout.splitlines():
        p = os.path.join(root, rel.strip())
        if os.path.exists(p):
            out.append(p)
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tracelint: trace-safety static analysis for "
                    "jit/shard_map/donation code")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the repo lint "
                         "surface: paddle_tpu/, bench.py, tools/)")
    ap.add_argument("--diff", metavar="REF",
                    help="analyze only .py files changed vs the git ref")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids or prefixes "
                         "(e.g. TL001,TL006 — or KL for every "
                         "kernellint rule)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", metavar="PATH",
                    help="baseline file (default: repo TRACELINT.md "
                         "when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline; exit 1 on any finding")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in core.all_rules():
            print(f"{rule.id} {rule.name} [{rule.severity}] — {rule.doc}")
        return 0

    if args.diff:
        paths = _diff_paths(args.diff)
    elif args.paths:
        paths = args.paths
    else:
        paths = default_paths()

    select = None
    if args.select:
        tokens = {t.strip() for t in args.select.split(",") if t.strip()}
        # a token is an exact id or a prefix: "KL" selects every
        # kernellint rule, "TL00" every tracelint rule
        select = {r.id for r in core.all_rules()
                  if any(r.id == t or r.id.startswith(t)
                         for t in tokens)}

    findings = core.run(paths, select=select)

    regressions: Optional[List[str]] = None
    if args.baseline:
        base_paths = [args.baseline]
    else:
        base_paths = baseline_mod.existing_ledgers()
    if base_paths and not args.no_baseline:
        base = baseline_mod.load_merged(base_paths)
        if select:
            base = {k: v for k, v in base.items() if k[0] in select}
        regressions = baseline_mod.compare(
            baseline_mod.counts(findings), base)

    # label the summary line by lane: a single-tool --select prints
    # that tool's name, anything mixed keeps the engine's default
    tools = {prefix: tool for _, prefix, tool in baseline_mod.LEDGERS}
    prefixes = {rid[:2] for rid in select} if select else set()
    label = tools.get(prefixes.pop(), "tracelint") if len(prefixes) == 1 \
        else "tracelint"

    if args.as_json:
        payload = {
            "version": 1,
            "findings": [f.to_json() for f in findings],
            "counts": {rule: sum(1 for f in findings if f.rule == rule)
                       for rule in sorted({f.rule for f in findings})},
            "baseline": (base_paths if regressions is not None
                         else None),
            "above_baseline": regressions or [],
        }
        print(json.dumps(payload, indent=1))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        if regressions is None:
            print(f"{label}: {n} finding{'s' if n != 1 else ''}")
        else:
            names = ", ".join(os.path.relpath(p, core.repo_root())
                              for p in base_paths)
            print(f"{label}: {n} finding{'s' if n != 1 else ''}, "
                  f"{len(regressions)} above baseline ({names})")
            for r in regressions:
                print(f"  ABOVE BASELINE: {r}")

    if regressions is not None:
        return 2 if regressions else 0
    return 1 if findings else 0
