"""tracelint engine: modules, findings, rules, suppressions, reachability.

The analyzer is a single-parse AST walker: every ``.py`` file under the
requested paths is parsed exactly once into a :class:`Module`, then every
registered :class:`Rule` runs over the shared module list.  Rules that
need cross-file context (TL005 collects the project's axis-name
vocabulary) get a ``prepare(modules)`` phase before per-module checks.

Trace-reachability — the analysis TL001/TL002 hang off — is computed
here, once per module: a function is *traced* if it is decorated with a
trace wrapper (``jit`` / ``to_static`` / ``partial(jax.jit, ...)`` /
``custom_vjp``), passed callable-first to one (``jax.jit(f)``,
``shard_map(f, ...)``, ``lax.scan(body, ...)``), or transitively called
by a traced function through a module-local name.  Anything XLA cannot
see — host syncs, side effects — inside that set is a latent hazard the
runtime only pays for later (recompiles, silent staleness, donation
corruption), which is exactly why it is checked at review time.

Suppressions use one syntax everywhere (including the NOTIMPL backend
and the KL kernel rules; ``kernellint:`` / ``locklint:`` are accepted
aliases for KL / LK suppressions so those files read naturally):

* ``# tracelint: disable=TL001,TL004`` on the finding's line
* ``# tracelint: disable`` on the line — every rule
* ``# tracelint: disable-file=TL006`` anywhere — whole file
* ``# kernellint: disable=KL006`` — same semantics, any spelling
* ``# locklint: disable=LK005`` — same semantics, any spelling

A suppression should carry a justification in the same comment or the
line above; ``docs/static_analysis.md`` documents the convention.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "Module", "Rule", "register", "all_rules", "load_module",
    "collect_files", "run", "SEVERITIES", "repo_root",
]

SEVERITIES = ("error", "warning", "info")

# names whose call traces the callable handed to them (or decorates one)
TRACE_WRAPPERS = {
    "jit", "to_static", "jit_compile", "shard_map", "scan", "vmap",
    "pmap", "grad", "value_and_grad", "vjp", "jvp", "custom_vjp",
    "custom_jvp", "checkpoint", "remat", "cond", "while_loop",
    "fori_loop", "switch", "associative_scan", "build_hybrid",
}

_SUPPRESS_RE = re.compile(
    r"#\s*(?:tracelint|kernellint|locklint):\s*disable(?:-file)?\s*"
    r"(?:=\s*([A-Z0-9, ]+))?")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*(?:tracelint|kernellint|locklint):\s*disable-file\s*=\s*([A-Z0-9, ]+)")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str                  # "TL001"
    severity: str              # error | warning | info
    path: str                  # repo-relative, "/"-separated
    line: int
    col: int
    message: str
    hint: str = ""

    @property
    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        s = f"{self.path}:{self.line}:{self.col} {self.rule} " \
            f"[{self.severity}] {self.message}"
        if self.hint:
            s += f" → {self.hint}"
        return s


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for an Attribute/Name chain, '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def tail_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class Module:
    """One parsed source file plus the derived facts rules share."""

    def __init__(self, path: str, rel: str, source: str, tree: ast.Module):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports = self._collect_imports()
        self.functions = self._collect_functions()
        self.traced = self._trace_reachable()
        self._line_disables, self._file_disables = self._collect_suppressions()

    # -- imports --------------------------------------------------------
    def _collect_imports(self) -> Dict[str, str]:
        """local alias -> full dotted module path (``np`` -> ``numpy``,
        ``random`` -> ``jax.random`` after ``from jax import random``)."""
        out: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and not node.level:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def resolve(self, node: ast.AST) -> str:
        """Fully-qualified dotted path of a Name/Attribute chain, with the
        root import alias expanded (``np.random.rand`` -> ``numpy.random.rand``)."""
        dotted = dotted_name(node)
        if not dotted:
            return ""
        root, _, rest = dotted.partition(".")
        full = self.imports.get(root, root)
        return f"{full}.{rest}" if rest else full

    # -- functions ------------------------------------------------------
    def _collect_functions(self):
        """Every (Async)FunctionDef keyed by bare name (last def wins),
        including nested defs — calls are resolved by bare name."""
        funcs: Dict[str, ast.AST] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs[node.name] = node
        return funcs

    # -- trace reachability --------------------------------------------
    def _is_trace_decorator(self, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            fn = dec.func
            if tail_name(fn) == "partial" and dec.args:
                return tail_name(dec.args[0]) in TRACE_WRAPPERS
            return tail_name(fn) in TRACE_WRAPPERS
        return tail_name(dec) in TRACE_WRAPPERS

    def _trace_reachable(self) -> Set[ast.AST]:
        entries: Set[str] = set()
        for name, fn in self.functions.items():
            if any(self._is_trace_decorator(d) for d in fn.decorator_list):
                entries.add(name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) \
                    and tail_name(node.func) in TRACE_WRAPPERS:
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        entries.add(arg.id)
                    elif isinstance(arg, ast.Call) \
                            and tail_name(arg.func) == "partial" and arg.args \
                            and isinstance(arg.args[0], ast.Name):
                        entries.add(arg.args[0].id)
        reach: Set[str] = set(entries)
        frontier = list(entries)
        while frontier:
            fn = self.functions.get(frontier.pop())
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name):
                    callee = node.func.id
                    if callee in self.functions and callee not in reach:
                        reach.add(callee)
                        frontier.append(callee)
        return {self.functions[n] for n in reach if n in self.functions}

    def traced_functions(self):
        """Traced function nodes, sorted by line for stable output."""
        return sorted(self.traced, key=lambda f: f.lineno)

    # -- suppressions ---------------------------------------------------
    def _collect_suppressions(self):
        line_dis: Dict[int, Optional[Set[str]]] = {}
        file_dis: Set[str] = set()
        for i, text in enumerate(self.lines, start=1):
            if "tracelint" not in text and "kernellint" not in text \
                    and "locklint" not in text:
                continue
            mf = _SUPPRESS_FILE_RE.search(text)
            if mf:
                file_dis.update(
                    t.strip() for t in mf.group(1).split(",") if t.strip())
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                ids = m.group(1)
                if ids:
                    line_dis[i] = {t.strip() for t in ids.split(",")
                                   if t.strip()}
                else:
                    line_dis[i] = None       # all rules on this line
        return line_dis, file_dis

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_disables:
            return True
        if line in self._line_disables:
            ids = self._line_disables[line]
            return ids is None or rule in ids
        return False


class Rule:
    """Base class: subclasses set ``id``/``severity``/``doc``/``hint``
    and implement ``check(module)``.  ``prepare(modules)`` runs once
    before any check for rules needing cross-file context."""

    id = "TL000"
    name = "unnamed"
    severity = "warning"
    doc = ""
    hint = ""

    def prepare(self, modules: Sequence[Module]) -> None:
        pass

    def check(self, module: Module) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str,
                hint: Optional[str] = None,
                severity: Optional[str] = None) -> Finding:
        return Finding(rule=self.id, severity=severity or self.severity,
                       path=module.rel, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       hint=self.hint if hint is None else hint)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> List[Rule]:
    # import side effect: rule modules self-register
    from . import rules as _rules            # noqa: F401
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# -- file collection / engine ------------------------------------------

def collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py") and os.path.exists(p):
            files.append(p)
    seen: Set[str] = set()
    out = []
    for f in files:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            out.append(f)
    return out


def load_module(path: str, root: Optional[str] = None) -> Optional[Module]:
    root = root or repo_root()
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source)
    except (OSError, SyntaxError, UnicodeDecodeError, ValueError):
        return None
    ap = os.path.abspath(path)
    rel = os.path.relpath(ap, root)
    if rel.startswith(".."):
        rel = ap
    return Module(ap, rel, source, tree)


def run(paths: Sequence[str], select: Optional[Set[str]] = None,
        root: Optional[str] = None) -> List[Finding]:
    """Analyze ``paths`` (files/dirs) with the selected rules; returns
    suppression-filtered findings sorted by (path, line, col, rule)."""
    modules = [m for m in (load_module(f, root)
                           for f in collect_files(paths)) if m]
    rules = [r for r in all_rules() if not select or r.id in select]
    for rule in rules:
        rule.prepare(modules)
    findings: List[Finding] = []
    for mod in modules:
        for rule in rules:
            for f in rule.check(mod):
                if not mod.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: f.sort_key)
    return findings
