"""LK002 — blocking call under a held lock.

The PR 13 invariant ("the driver thread never touches a socket")
generalized: while a lock is held, nothing unbounded may block — a
slow peer, a full queue, or a stuck engine step turns lock contention
into a system-wide stall, and if the blocked operation needs another
thread that wants the same lock, into a deadlock.  The serving stack's
``_Delivery`` pattern (mutate handles OUTSIDE the scheduler lock) and
``stream_from``'s lock-released yields exist precisely to satisfy this.

Flagged while ≥1 lock is held:

* ``time.sleep`` / ``socket.create_connection``
* socket ops (``sendall``/``recv``/``recvfrom``/``accept``; ``read``/
  ``readline``/``write``/``flush`` on ``rfile``/``wfile``/``sock``/
  ``conn`` receivers)
* ``engine.step`` — one step is an unbounded device round-trip
* ``block_until_ready``
* ``queue.get()`` / ``queue.put(item)`` on a known ``queue.Queue``
  attribute, with no timeout
* ``.join()`` on a known thread attribute, with no timeout
* ``Event.wait()`` with no timeout

A ``Condition.wait`` under its *own* condition is the CV idiom (wait
releases the lock) and is LK004's domain, not a finding here.
"""

from __future__ import annotations

import ast

from .. import core
from . import model

_SOCKET_METHODS = {"sendall", "recv", "recvfrom", "accept"}
_SOCKET_FILE_METHODS = {"read", "readline", "write", "flush"}
_SOCKET_RECEIVERS = {"rfile", "wfile", "sock", "conn", "connection"}


def _has_timeout(call: ast.Call, max_pos: int) -> bool:
    """True if the call passes a timeout (kwarg, or enough positional
    args to reach the timeout slot)."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    return len(call.args) >= max_pos


def _self_attr(fn: ast.AST) -> str:
    """``self.X.m`` -> ``X`` (else '')."""
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Attribute) \
            and isinstance(fn.value.value, ast.Name) \
            and fn.value.value.id == "self":
        return fn.value.attr
    return ""


def blocking_reason(mm: model.ModuleModel, site: model.CallSite) -> str:
    """Why this call is unbounded-blocking, or '' if it isn't."""
    call = site.node
    fn = call.func
    tail = core.tail_name(fn)
    resolved = mm.module.resolve(fn)
    if resolved == "time.sleep":
        return "time.sleep"
    if tail == "create_connection" and resolved.startswith("socket."):
        return "socket.create_connection"
    if tail in _SOCKET_METHODS:
        return f"socket .{tail}"
    recv_tail = core.tail_name(fn.value) if isinstance(fn, ast.Attribute) \
        else ""
    if tail in _SOCKET_FILE_METHODS and recv_tail in _SOCKET_RECEIVERS:
        return f"socket file .{tail} on '{recv_tail}'"
    if tail == "connect" and recv_tail in _SOCKET_RECEIVERS:
        return "socket .connect"
    if tail == "step" and recv_tail == "engine":
        return "engine.step (unbounded device round-trip)"
    if tail == "block_until_ready":
        return "block_until_ready"
    cm = mm.classes.get(site.cls)
    attr = _self_attr(fn)
    if cm is not None and attr:
        if tail == "get" and attr in cm.queue_attrs \
                and not _has_timeout(call, max_pos=2):
            return f"queue .get() on 'self.{attr}' with no timeout"
        if tail == "put" and attr in cm.queue_attrs \
                and not _has_timeout(call, max_pos=3):
            return f"queue .put() on 'self.{attr}' with no timeout"
        if tail == "join" and attr in cm.thread_attrs \
                and not _has_timeout(call, max_pos=1):
            return f"thread .join() on 'self.{attr}' with no timeout"
        if tail == "wait" and attr in cm.event_attrs \
                and not _has_timeout(call, max_pos=1):
            return f"Event .wait() on 'self.{attr}' with no timeout"
    return ""


@core.register
class BlockingUnderLockRule(core.Rule):
    id = "LK002"
    name = "blocking-under-lock"
    severity = "error"
    doc = ("unbounded blocking call (socket, sleep, engine.step, "
           "no-timeout queue/join/wait) while a lock is held")
    hint = ("move the blocking call outside the lock (collect work "
            "under the lock, act after releasing — the _Delivery "
            "pattern), or bound it with a timeout")

    def check(self, module: core.Module):
        mm = model.get_model(module)
        for site in mm.calls:
            if not site.held:
                continue
            # Condition.wait under its own condition: the CV idiom
            fn = site.node.func
            if core.tail_name(fn) == "wait" \
                    and isinstance(fn, ast.Attribute):
                ref = mm.resolve_lock(fn.value, site.cls)
                if ref is not None and ref.kind == "condition" \
                        and any(h.id == ref.id for h in site.held):
                    continue
            reason = blocking_reason(mm, site)
            if reason:
                held = ", ".join(h.id.split("::")[-1] for h in site.held)
                yield self.finding(
                    module, site.node,
                    f"{reason} while holding [{held}]")
