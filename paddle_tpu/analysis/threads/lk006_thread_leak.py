"""LK006 — thread started without a reachable join on the shutdown
path.

A thread nobody joins outlives the object that started it: close()
returns while the worker still runs, tests leak threads into each
other, and daemon threads get killed mid-write at interpreter exit.
Every long-lived thread in this codebase pairs its ``start()`` with a
``join`` somewhere on the owner's shutdown path (``stop()``/
``close()``), usually with a bounded timeout; this rule checks the
pairing exists.

Resolution is lexical within the module: a thread bound to ``self.X``
needs a ``self.X.join(...)`` (or ``t = self.X; t.join(...)`` — the
single-assignment alias the model tracks), a local binding needs a
join on that name, and an unbound ``threading.Thread(...).start()``
can never be joined at all.  Deliberate fire-and-forget threads (a
signal-triggered shutdown thread that must not be waited on) get a
justified ``# locklint: disable=LK006``.
"""

from __future__ import annotations

from .. import core
from . import model


@core.register
class ThreadLeakRule(core.Rule):
    id = "LK006"
    name = "unjoined-thread"
    severity = "warning"
    doc = ("threading.Thread created with no join() on its binding "
           "anywhere in the module: the shutdown path cannot wait for "
           "it, so it leaks past close()")
    hint = ("keep a reference and join it (bounded timeout) from the "
            "owner's stop()/close(); suppress with "
            "'# locklint: disable=LK006' + justification for "
            "deliberate fire-and-forget threads")

    def check(self, module: core.Module):
        mm = model.get_model(module)
        # attribute binds match joins by trailing attribute name too:
        # `srv._serve_thread = Thread(...)` is cleared by a
        # `self._serve_thread.join()` elsewhere in the module — the
        # receiver spelling differs across methods but the slot is one
        join_tails = {t.rsplit(".", 1)[-1] for t in mm.join_targets}
        for ts in mm.threads:
            if ts.bind and ts.bind in mm.join_targets:
                continue
            if "." in ts.bind \
                    and ts.bind.rsplit(".", 1)[-1] in join_tails:
                continue
            role, target = mm._thread_role(ts.node)
            what = f"thread '{role[7:]}'" if role != "thread:anonymous" \
                else "thread"
            if not ts.bind:
                yield self.finding(
                    module, ts.node,
                    f"{what} is started without binding the Thread "
                    f"object — it can never be joined")
            else:
                yield self.finding(
                    module, ts.node,
                    f"{what} bound to '{ts.bind}' is never joined in "
                    f"this module — no shutdown path waits for it")
