"""locklint (LK) — static concurrency safety for the threaded surface.

The serving arc (PRs 7, 11-13) turned the repo into a multi-threaded
system: frontend driver threads, HTTP handler threads, housekeeper and
shutdown threads, the AsyncCheckpointer writer, device/host
prefetchers, elastic heartbeat loops.  Every one of those PRs fixed at
least one hand-found threading bug; locklint machine-checks the
invariants those fixes established, the way tracelint checks trace
purity and kernellint checks Pallas kernels.

``model.py`` builds the shared facts per module — lock definitions
(``self._lock = threading.Lock()``), thread roles (entry points from
``threading.Thread(target=...)``, handler-class methods, ``__del__``/
``atexit`` finalizers), per-scope held-lock tracking through nested
``with lock:`` blocks, and the project-wide lock-acquisition-order
graph — and the six LK rules hang off it:

* LK001 — shared mutable attribute written from ≥2 thread roles with
  no common lock
* LK002 — blocking call under a held lock (the PR 13 "driver thread
  never touches a socket" invariant, generalized)
* LK003 — lock-acquisition-order cycle in the project-wide graph
* LK004 — condition-variable ``wait`` not guarded by a ``while`` loop
* LK005 — finalizer touching locked state or joining threads
* LK006 — thread started without a reachable ``join`` on shutdown

Suppress with ``# locklint: disable=LKxxx`` plus a justification; the
debt ledger is ``LOCKLINT.md`` (empty — any finding is above
baseline).  The LK003 graph is validated by execution through
``observability.traced_lock.TracedLock`` (see tests/test_locklint.py),
the way KL001's cost model is validated by interpret-mode byte capture.
"""

from . import model  # noqa: F401

__all__ = ["model"]
