"""LK003 — lock-acquisition-order cycle in the project-wide graph.

Two threads acquiring the same pair of locks in opposite orders is the
classic ABBA deadlock; it needs no unlucky timing to be wrong, only to
exist.  The model builds a directed graph over every lock the
structure pass can identify: an edge A→B for each ``with B:`` nested
inside a held A (same function), plus one level of call closure — a
call made while holding A, resolved to a concrete callee (same-class
``self.m()``, module function, or ``self.attr.m()`` through an
annotated attribute type), contributes A→⟨each lock the callee
acquires at its own top level⟩.  Any strongly-connected component with
more than one lock is a potential deadlock; each edge inside one is
reported where it is witnessed.

The same graph is the reference for the runtime cross-check:
``observability.traced_lock.TracedLock`` records the acquisition
order a live threaded-serving test actually executes, and the test
asserts every observed edge is present here (the static graph is an
over-approximation of execution, never the reverse).
"""

from __future__ import annotations

import types
from typing import List, Sequence, Set

from .. import core
from . import model


@core.register
class LockOrderRule(core.Rule):
    id = "LK003"
    name = "lock-order-cycle"
    severity = "error"
    doc = ("a cycle in the project-wide lock-acquisition-order graph "
           "(nested `with` blocks + one level of call closure): two "
           "threads taking the locks in opposite orders can deadlock")
    hint = ("pick one global order for the locks involved and acquire "
            "in that order everywhere, or collapse them into one lock")

    def __init__(self):
        self._project: model.ProjectModel = None  # set in prepare()
        self._cyclic: List[Set[str]] = []

    def prepare(self, modules: Sequence[core.Module]) -> None:
        self._project = model.ProjectModel(modules)
        self._cyclic = [set(c) for c in self._project.cycles()]

    def check(self, module: core.Module):
        if self._project is None or not self._cyclic:
            return
        for (a, b), (rel, line) in sorted(self._project.edges.items(),
                                          key=lambda kv: kv[1][1]):
            if rel != module.rel:
                continue
            for comp in self._cyclic:
                if a in comp and b in comp:
                    order = " -> ".join(sorted(comp))
                    yield self.finding(
                        module,
                        types.SimpleNamespace(lineno=line, col_offset=0),
                        f"acquisition edge {a.split('::')[-1]} -> "
                        f"{b.split('::')[-1]} participates in a "
                        f"lock-order cycle [{order}]")
                    break
