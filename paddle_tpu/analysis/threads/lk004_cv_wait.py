"""LK004 — condition-variable ``wait`` not guarded by a ``while`` loop.

``Condition.wait`` can return spuriously, and a ``notify_all`` can
wake a thread whose predicate a third thread already consumed — so
the predicate must be re-checked in a loop, never assumed from the
wakeup.  ``if not ready: cond.wait()`` is the textbook missed-wakeup
bug; ``while True: ... cond.wait(t)`` with in-loop re-checks (the
RequestHandle pattern in serving/frontend.py) is fine, because the
loop re-evaluates state every iteration.  Only ``while`` counts as a
guard: a ``for`` body does not re-check a predicate after a wakeup.
"""

from __future__ import annotations

from .. import core
from . import model


@core.register
class CvWaitRule(core.Rule):
    id = "LK004"
    name = "unguarded-cv-wait"
    severity = "error"
    doc = ("Condition.wait() outside a while loop: spurious wakeups "
           "and consumed notifications make the post-wait state "
           "unknowable without re-checking the predicate in a loop")
    hint = ("wrap the wait in 'while not <predicate>: cond.wait(...)' "
            "(or an equivalent re-checking while loop)")

    def check(self, module: core.Module):
        mm = model.get_model(module)
        for w in mm.waits:
            if w.in_while:
                continue
            yield self.finding(
                module, w.node,
                f"wait() on condition '{w.lock.cls}.{w.lock.attr}' is "
                f"not inside a while loop — the woken predicate is "
                f"never re-checked")
