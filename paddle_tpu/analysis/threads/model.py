"""Shared thread-role / lock model the LK rules hang off.

Built once per :class:`~paddle_tpu.analysis.core.Module` (cached by
module identity) in two passes:

1. **Structure pass** — per class: which ``self.X`` attributes hold
   locks (``threading.Lock/RLock/Condition/Semaphore``), threads,
   queues, events; which attributes carry a known class type (from
   ``self.X = param`` where the ``__init__`` parameter is annotated, or
   ``self.X = ClassName(...)``); plus module-level lock variables and
   handler classes (bases named ``*RequestHandler`` / ``ThreadingMixIn``).

2. **Semantic pass** — a context-carrying recursive walk recording, for
   every statement, the stack of held locks (entered ``with lock:``
   blocks), and from it: lock acquisitions (with the held stack at
   entry — the edges of the LK003 order graph), call sites under held
   locks (LK002 and the one-level call closure), attribute write sites
   (LK001), condition ``wait`` calls and whether a ``while`` loop
   guards them (LK004), ``Thread(...)`` creations and ``.join()`` sites
   (LK006), and ``atexit.register`` targets (LK005).

Thread **roles** are then propagated: ``threading.Thread(target=...)``
entry points, handler-class methods, and ``__del__``/``atexit``
finalizers seed their role; every public function seeds ``main`` (any
externally-driven thread).  Roles flow transitively through bare-name
calls within the module — the same resolution the tracelint
reachability pass uses — so a private helper reached only from a
driver loop carries only the driver's role.

Lock identity is ``<module-rel>::<Class>.<attr>`` (or ``::<name>`` for
module-level locks) — the same ids ``observability.traced_lock`` uses,
so the static LK003 graph and the runtime-observed acquisition order
compare directly.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import core

LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
              "Semaphore": "semaphore", "BoundedSemaphore": "semaphore"}
QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}

ROLE_MAIN = "main"
ROLE_HANDLER = "handler"
ROLE_FINALIZER = "finalizer"

_HANDLER_BASE_HINTS = ("RequestHandler", "ThreadingMixIn")


@dataclasses.dataclass(frozen=True)
class LockRef:
    """One lock object, identified by where it is defined."""
    module: str          # repo-relative path of the defining module
    cls: str             # owning class name, "" for module-level
    attr: str            # attribute / variable name
    kind: str            # lock | rlock | condition | semaphore

    @property
    def id(self) -> str:
        owner = f"{self.cls}.{self.attr}" if self.cls else self.attr
        return f"{self.module}::{owner}"


@dataclasses.dataclass
class Acquisition:
    lock: LockRef
    node: ast.AST                  # the with-item context expression
    func: Optional[ast.AST]        # enclosing function (None at module level)
    held_before: Tuple[LockRef, ...]


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    func: Optional[ast.AST]
    held: Tuple[LockRef, ...]
    cls: str                       # enclosing class name or ""
    recv_type: str = ""            # receiver's class-name tail, if typed


@dataclasses.dataclass
class WriteSite:
    cls: str
    attr: str
    node: ast.AST
    func: Optional[ast.AST]
    held: Tuple[LockRef, ...]


@dataclasses.dataclass
class WaitSite:
    lock: LockRef                  # the condition being waited on
    node: ast.Call
    func: Optional[ast.AST]
    held: Tuple[LockRef, ...]
    in_while: bool                 # a while loop encloses the wait


@dataclasses.dataclass
class ThreadSite:
    node: ast.Call                 # the threading.Thread(...) call
    func: Optional[ast.AST]
    cls: str                       # enclosing class name or ""
    bind: str                      # "self.X" / "X" / "" (unbound)
    daemon: bool


class ClassModel:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.lock_attrs: Dict[str, str] = {}     # attr -> kind
        self.thread_attrs: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.event_attrs: Set[str] = set()
        self.attr_types: Dict[str, str] = {}     # attr -> class-name tail
        self.methods: Dict[str, ast.AST] = {}
        self.is_handler = any(
            h in core.tail_name(b) for b in node.bases
            for h in _HANDLER_BASE_HINTS)


def _ctor_tail(value: ast.AST) -> str:
    if isinstance(value, ast.Call):
        return core.tail_name(value.func)
    return ""


class ModuleModel:
    """All LK-relevant facts for one module."""

    def __init__(self, module: core.Module):
        self.module = module
        self.classes: Dict[str, ClassModel] = {}
        self.module_locks: Dict[str, str] = {}         # name -> kind
        self.acquisitions: List[Acquisition] = []
        self.calls: List[CallSite] = []
        self.writes: List[WriteSite] = []
        self.waits: List[WaitSite] = []
        self.threads: List[ThreadSite] = []
        self.join_targets: Set[str] = set()            # "self.X" / "X" joined
        self.atexit_targets: Set[str] = set()          # bare function names
        self.func_calls: Dict[int, Set[str]] = {}      # id(func) -> callees
        # id(func) -> callee keys: ("cls", Class, m) for self/typed-attr
        # calls resolved in-module, ("name", m) for everything the
        # receiver leaves open, ("extern",) for calls that provably
        # leave the module (typed attr of a non-project class)
        self.func_call_targets: Dict[int, Set[Tuple]] = {}
        self.func_class: Dict[int, str] = {}           # id(func) -> class name
        self.func_index: Dict[int, ast.AST] = {}       # id(func) -> node
        self.nested_funcs: Set[int] = set()            # defs inside a def
        self._by_name: Dict[str, List[ast.AST]] = {}
        self.roles: Dict[int, Set[str]] = {}           # id(func) -> roles
        self.role_of_entry: Dict[int, Set[str]] = {}
        self._structure_pass()
        _SemanticWalker(self).walk()
        for fn in self.func_index.values():
            self._by_name.setdefault(getattr(fn, "name", ""), []).append(fn)
        self._propagate_roles()

    # -- structure ------------------------------------------------------
    def _structure_pass(self) -> None:
        mod = self.module
        for node in mod.tree.body:
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, val = node.target, node.value
            if isinstance(tgt, ast.Name):
                kind = LOCK_CTORS.get(_ctor_tail(val))
                if kind:
                    self.module_locks[tgt.id] = kind
        for cnode in ast.walk(mod.tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            cm = ClassModel(cnode)
            self.classes[cm.name] = cm
            for sub in cnode.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cm.methods[sub.name] = sub
            init = cm.methods.get("__init__")
            ann: Dict[str, str] = {}
            if init is not None:
                for a in list(init.args.args) + list(init.args.kwonlyargs):
                    if a.annotation is not None:
                        t = core.tail_name(a.annotation)
                        if not t and isinstance(a.annotation, ast.Constant) \
                                and isinstance(a.annotation.value, str):
                            t = a.annotation.value.split(".")[-1]
                        if t:
                            ann[a.arg] = t
            for m in cm.methods.values():
                for node in ast.walk(m):
                    tgt = val = anno = None
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        tgt, val = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        tgt, val, anno = node.target, node.value, \
                            node.annotation
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    tail = _ctor_tail(val) if val is not None else ""
                    kind = LOCK_CTORS.get(tail)
                    if kind:
                        cm.lock_attrs[tgt.attr] = kind
                    elif tail == "Thread":
                        cm.thread_attrs.add(tgt.attr)
                    elif tail in QUEUE_CTORS:
                        cm.queue_attrs.add(tgt.attr)
                    elif tail == "Event":
                        cm.event_attrs.add(tgt.attr)
                    elif tail and tail[0].isupper() \
                            and tgt.attr not in cm.attr_types:
                        cm.attr_types[tgt.attr] = tail
                    elif isinstance(val, ast.Name) and val.id in ann:
                        cm.attr_types[tgt.attr] = ann[val.id]
                    elif anno is not None \
                            and tgt.attr not in cm.attr_types:
                        # `self.x: Dict[...] = {}` — the annotation tail
                        # types the attribute (Dict/List/... count: they
                        # prove the receiver is not a project class)
                        t = core.tail_name(anno)
                        if not t and isinstance(anno, ast.Subscript):
                            t = core.tail_name(anno.value)
                        if t and t[0].isupper():
                            cm.attr_types[tgt.attr] = t

    # -- lock resolution ------------------------------------------------
    def resolve_lock(self, expr: ast.AST, cls: str,
                     project: Optional["ProjectModel"] = None
                     ) -> Optional[LockRef]:
        """``self.X`` / module-level ``X`` / ``self.A.B`` (via the
        annotated type of ``A``) -> LockRef, else None."""
        rel = self.module.rel
        if isinstance(expr, ast.Name):
            kind = self.module_locks.get(expr.id)
            if kind:
                return LockRef(rel, "", expr.id, kind)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            cm = self.classes.get(cls)
            if cm and expr.attr in cm.lock_attrs:
                return LockRef(rel, cls, expr.attr, cm.lock_attrs[expr.attr])
            return None
        # self.A.B — B on the annotated/constructed type of attribute A
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and cls:
            cm = self.classes.get(cls)
            tname = cm.attr_types.get(base.attr) if cm else None
            if not tname:
                return None
            if tname in self.classes:
                tcm = self.classes[tname]
                if expr.attr in tcm.lock_attrs:
                    return LockRef(rel, tname, expr.attr,
                                   tcm.lock_attrs[expr.attr])
            elif project is not None and tname in project.class_index:
                omm, tcm = project.class_index[tname]
                if expr.attr in tcm.lock_attrs:
                    return LockRef(omm.module.rel, tname, expr.attr,
                                   tcm.lock_attrs[expr.attr])
        return None

    # -- roles ----------------------------------------------------------
    def _thread_role(self, call: ast.Call) -> Tuple[str, Optional[str]]:
        """(role name, target bare name or None) for a Thread(...) call."""
        target = None
        label = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = core.tail_name(kw.value)
            elif kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                label = kw.value.value
        role = f"thread:{label or target or 'anonymous'}"
        return role, target

    def _propagate_roles(self) -> None:
        entries: List[Tuple[ast.AST, str]] = []
        for ts in self.threads:
            role, target = self._thread_role(ts.node)
            if not target:
                continue
            fn = None
            cm = self.classes.get(ts.cls) if ts.cls else None
            if cm is not None and target in cm.methods:
                fn = cm.methods[target]
            elif target in self.module.functions:
                fn = self.module.functions[target]
            if fn is not None:
                entries.append((fn, role))
        for cm in self.classes.values():
            fin = cm.methods.get("__del__")
            if fin is not None:
                entries.append((fin, ROLE_FINALIZER))
            if cm.is_handler:
                for m in cm.methods.values():
                    entries.append((m, ROLE_HANDLER))
        for name in self.atexit_targets:
            fn = self.module.functions.get(name)
            if fn is not None:
                entries.append((fn, ROLE_FINALIZER))
        # main: every public function/method not owned by a handler
        # class — nested defs are only callable through their enclosing
        # function, so they inherit roles via propagation instead
        for fid, fn in self.func_index.items():
            if fid in self.nested_funcs:
                continue
            name = getattr(fn, "name", "")
            cls = self.func_class.get(fid, "")
            cm = self.classes.get(cls)
            public = not name.startswith("_") or (
                name.startswith("__") and name.endswith("__")
                and name != "__del__")
            if public and not (cm and cm.is_handler):
                entries.append((fn, ROLE_MAIN))
        # propagate each role through resolved call targets: precise for
        # self/typed-attr calls, bare-name over-approximation otherwise
        for fn, role in entries:
            frontier = [fn]
            seen: Set[int] = set()
            while frontier:
                f = frontier.pop()
                if id(f) in seen:
                    continue
                seen.add(id(f))
                self.roles.setdefault(id(f), set()).add(role)
                frontier.extend(self.call_targets(id(f)))

    def call_targets(self, fid: int) -> List[ast.AST]:
        """In-module function nodes a function's calls can reach."""
        out: List[ast.AST] = []
        for key in self.func_call_targets.get(fid, ()):
            if key[0] == "cls":
                cm = self.classes.get(key[1])
                got = cm.methods.get(key[2]) if cm else None
                if got is not None:
                    out.append(got)
            elif key[0] == "name":
                out.extend(self._by_name.get(key[1], ()))
        return out

    def roles_of(self, func: Optional[ast.AST]) -> Set[str]:
        if func is None:
            return {ROLE_MAIN}
        return self.roles.get(id(func), {ROLE_MAIN})


class _SemanticWalker:
    """Recursive statement walker carrying (class, function, held-locks,
    while-depth) context."""

    def __init__(self, mm: ModuleModel):
        self.mm = mm
        self.cls = ""
        self.func: Optional[ast.AST] = None
        self.held: List[LockRef] = []
        self.while_depth = 0
        self.locals: Dict[str, ast.AST] = {}    # single-assign local -> value
        self.param_types: Dict[str, str] = {}   # annotated param -> type tail

    def walk(self) -> None:
        for stmt in self.mm.module.tree.body:
            self._stmt(stmt)

    # -- dispatch -------------------------------------------------------
    def _stmt(self, node: ast.AST) -> None:
        mm = self.mm
        if isinstance(node, ast.ClassDef):
            prev_cls, prev_fn = self.cls, self.func
            self.cls, self.func = node.name, None
            for sub in node.body:
                self._stmt(sub)
            self.cls, self.func = prev_cls, prev_fn
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mm.func_index[id(node)] = node
            mm.func_class[id(node)] = self.cls
            mm.func_calls.setdefault(id(node), set())
            if self.func is not None:
                mm.nested_funcs.add(id(node))
            prev_fn, prev_held, prev_while = \
                self.func, self.held, self.while_depth
            prev_locals, prev_params = self.locals, self.param_types
            self.func, self.held, self.while_depth = node, [], 0
            self.locals = {}
            self.param_types = {}
            for a in list(node.args.args) + list(node.args.kwonlyargs):
                if a.annotation is not None:
                    t = core.tail_name(a.annotation)
                    if not t and isinstance(a.annotation, ast.Subscript):
                        t = core.tail_name(a.annotation.value)
                    if t:
                        self.param_types[a.arg] = t
            for sub in node.body:
                self._stmt(sub)
            self.func, self.held, self.while_depth = \
                prev_fn, prev_held, prev_while
            self.locals, self.param_types = prev_locals, prev_params
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[LockRef] = []
            for item in node.items:
                self._expr(item.context_expr)
                ref = mm.resolve_lock(item.context_expr, self.cls)
                if ref is None and isinstance(item.context_expr, ast.Name):
                    alias = self.locals.get(item.context_expr.id)
                    if alias is not None:
                        ref = mm.resolve_lock(alias, self.cls)
                if ref is not None:
                    mm.acquisitions.append(Acquisition(
                        ref, item.context_expr, self.func,
                        tuple(self.held)))
                    self.held.append(ref)
                    acquired.append(ref)
            for sub in node.body:
                self._stmt(sub)
            for _ in acquired:
                self.held.pop()
            return
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            self._expr(getattr(node, "test", None)
                       or getattr(node, "iter", None))
            # only a while loop re-checks its predicate after a wakeup,
            # so only While counts as the LK004 guard
            guard = isinstance(node, ast.While)
            self.while_depth += 1 if guard else 0
            for sub in node.body:
                self._stmt(sub)
            self.while_depth -= 1 if guard else 0
            for sub in node.orelse:
                self._stmt(sub)
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            self._record_write_targets(node.targets, node)
            bind = ""
            if len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    # single-assignment alias tracking only: a rebound
                    # name no longer resolves (conservative)
                    if tgt.id in self.locals:
                        self.locals[tgt.id] = ast.Constant(value=None)
                    else:
                        self.locals[tgt.id] = node.value
                    bind = tgt.id
                elif isinstance(tgt, ast.Attribute):
                    bind = core.dotted_name(tgt)
            self._maybe_thread(node.value, bind_name=bind)
            return
        if isinstance(node, ast.AugAssign):
            self._expr(node.value)
            self._record_write_targets([node.target], node)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._expr(node.value)
                self._record_write_targets([node.target], node)
                self._maybe_thread(
                    node.value,
                    bind_name=core.dotted_name(node.target) or "")
            return
        if isinstance(node, ast.Expr):
            self._maybe_thread(node.value, bind_name="")
            self._expr(node.value)
            return
        if isinstance(node, (ast.Return, ast.Raise)):
            self._expr(getattr(node, "value", None)
                       or getattr(node, "exc", None))
            return
        # generic statements (If / Try / ...): recurse into child
        # statements, except-handler bodies, and expressions
        for field in ast.iter_child_nodes(node):
            if isinstance(field, ast.stmt):
                self._stmt(field)
            elif isinstance(field, ast.ExceptHandler):
                self._expr(field.type)
                for sub in field.body:
                    self._stmt(sub)
            else:
                self._expr(field)

    def _record_write_targets(self, targets: Sequence[ast.AST],
                              node: ast.AST) -> None:
        for tgt in targets:
            for t in self._flatten_target(tgt):
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and self.cls:
                    self.mm.writes.append(WriteSite(
                        self.cls, t.attr, node, self.func,
                        tuple(self.held)))
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Attribute) \
                        and isinstance(t.value.value, ast.Name) \
                        and t.value.value.id == "self" and self.cls:
                    self.mm.writes.append(WriteSite(
                        self.cls, t.value.attr, node, self.func,
                        tuple(self.held)))

    @staticmethod
    def _flatten_target(tgt: ast.AST) -> Iterable[ast.AST]:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                yield from _SemanticWalker._flatten_target(e)
        else:
            yield tgt

    def _maybe_thread(self, value: ast.AST, bind_name: str) -> None:
        if not isinstance(value, ast.Call):
            return
        # chained `threading.Thread(...).start()` — unbound by
        # construction, so the bind name is dropped regardless
        if core.tail_name(value.func) == "start" \
                and isinstance(value.func, ast.Attribute) \
                and isinstance(value.func.value, ast.Call) \
                and core.tail_name(value.func.value.func) == "Thread":
            value, bind_name = value.func.value, ""
        if core.tail_name(value.func) == "Thread":
            daemon = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in value.keywords)
            self.mm.threads.append(ThreadSite(
                value, self.func, self.cls, bind_name, daemon))

    # -- expressions ----------------------------------------------------
    _MUTATORS = {"append", "extend", "pop", "popitem", "popleft",
                 "update", "add", "remove", "discard", "clear",
                 "insert", "setdefault", "appendleft"}

    def _expr(self, node: Optional[ast.AST]) -> None:
        if node is None:
            return
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            self._call(call)

    def _typed_key(self, tname: str, tail: str) -> Optional[Tuple]:
        """Callee key for a method call on a receiver of known type
        ``tname`` — in-module class dispatches precisely, any other
        known type (dict, Queue, socket, ...) provably leaves the
        module."""
        if not tname:
            return None
        if tname in self.mm.classes:
            return ("cls", tname, tail)
        return ("extern",)

    def _callee_key(self, fn: ast.AST) -> Tuple:
        tail = core.tail_name(fn)
        if isinstance(fn, ast.Name):
            return ("name", tail)
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and self.cls:
                return ("cls", self.cls, tail)
            if isinstance(recv, ast.Name):
                key = self._typed_key(
                    self.param_types.get(recv.id, ""), tail)
                if key is None:
                    alias = self.locals.get(recv.id)
                    t = _ctor_tail(alias) if alias is not None else ""
                    if t and t[0].isupper():
                        key = self._typed_key(t, tail)
                if key is not None:
                    return key
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" and self.cls:
                cm = self.mm.classes.get(self.cls)
                tname = cm.attr_types.get(recv.attr) if cm else None
                if tname:
                    # typed attribute: in-module class -> that method
                    # only; any other type provably leaves the module
                    if tname in self.mm.classes:
                        return ("cls", tname, tail)
                    return ("extern",)
            return ("name", tail)
        return ("extern",)

    def _recv_type(self, fn: ast.AST) -> str:
        """Class-name tail of a method call's receiver, when the walker
        can type it: parameter annotations, single-assignment local
        constructor aliases, and annotated ``self.X`` attributes."""
        if not isinstance(fn, ast.Attribute):
            return ""
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id != "self":
            t = self.param_types.get(recv.id, "")
            if not t:
                alias = self.locals.get(recv.id)
                t = _ctor_tail(alias) if alias is not None else ""
            return t if t and t[0].isupper() else ""
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and self.cls:
            cm = self.mm.classes.get(self.cls)
            return (cm.attr_types.get(recv.attr, "") if cm else "")
        return ""

    def _call(self, call: ast.Call) -> None:
        mm = self.mm
        tail = core.tail_name(call.func)
        if self.func is not None:
            mm.func_calls.setdefault(id(self.func), set()).add(tail)
            mm.func_call_targets.setdefault(id(self.func), set()).add(
                self._callee_key(call.func))
        mm.calls.append(CallSite(call, self.func, tuple(self.held),
                                 self.cls, self._recv_type(call.func)))
        fn = call.func
        # atexit.register(f) — the finalizer role's other entry point
        if tail == "register" \
                and mm.module.resolve(fn).startswith("atexit."):
            if call.args:
                mm.atexit_targets.add(core.tail_name(call.args[0]))
        # mutating method call on self.X counts as a write to X
        if tail in self._MUTATORS and isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Attribute) \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id == "self" and self.cls:
            mm.writes.append(WriteSite(
                self.cls, fn.value.attr, call, self.func,
                tuple(self.held)))
        # condition-variable wait
        if tail == "wait" and isinstance(fn, ast.Attribute):
            ref = mm.resolve_lock(fn.value, self.cls)
            if ref is not None and ref.kind == "condition":
                mm.waits.append(WaitSite(ref, call, self.func,
                                         tuple(self.held),
                                         self.while_depth > 0))
        # join sites, for LK006 (thread joined somewhere in the module)
        if tail == "join" and isinstance(fn, ast.Attribute):
            recv = fn.value
            name = core.dotted_name(recv)
            if name:
                mm.join_targets.add(name)
                if isinstance(recv, ast.Name):
                    alias = self.locals.get(recv.id)
                    aname = core.dotted_name(alias) if alias is not None \
                        else ""
                    if aname:
                        mm.join_targets.add(aname)


# cached per-module models, keyed by module identity (modules are
# parsed once per run, so id() is stable for a run's lifetime)
_MODEL_CACHE: Dict[int, ModuleModel] = {}


def get_model(module: core.Module) -> ModuleModel:
    key = id(module)
    got = _MODEL_CACHE.get(key)
    if got is None or got.module is not module:
        got = _MODEL_CACHE[key] = ModuleModel(module)
    return got


class ProjectModel:
    """Cross-module facts: the class index and the LK003 lock-order
    graph (nested acquisitions + one level of call closure)."""

    def __init__(self, modules: Sequence[core.Module]):
        self.models = [get_model(m) for m in modules]
        self.class_index: Dict[str, Tuple[ModuleModel, ClassModel]] = {}
        for mm in self.models:
            for cm in mm.classes.values():
                self.class_index.setdefault(cm.name, (mm, cm))
        # function index: bare name -> [(model, class name, func node)]
        self.func_index: Dict[str, List[Tuple[ModuleModel, str, ast.AST]]] \
            = {}
        for mm in self.models:
            for fid, fn in mm.func_index.items():
                self.func_index.setdefault(
                    getattr(fn, "name", ""), []).append(
                    (mm, mm.func_class.get(fid, ""), fn))
        # direct acquisitions per function
        self.func_acqs: Dict[int, List[Acquisition]] = {}
        for mm in self.models:
            for acq in mm.acquisitions:
                if acq.func is not None:
                    self.func_acqs.setdefault(id(acq.func), []).append(acq)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._build_graph()

    # -- graph ----------------------------------------------------------
    def _add_edge(self, src: LockRef, dst: LockRef, rel: str,
                  line: int) -> None:
        if src.id == dst.id:
            return                       # RLock re-entry, not an ordering
        self.edges.setdefault((src.id, dst.id), (rel, line))

    def _callees(self, mm: ModuleModel, site: CallSite
                 ) -> List[ast.AST]:
        """Precise one-level callee resolution: same-class ``self.m()``,
        module/global functions by bare name, and typed receivers — the
        walker records a receiver's class-name tail on the CallSite from
        parameter annotations, local constructor aliases, and annotated
        ``self.X`` attributes.  Unresolvable receivers resolve to
        nothing — the graph prefers soundness-per-edge over recall."""
        fn = site.node.func
        tail = core.tail_name(fn)
        out: List[ast.AST] = []
        if isinstance(fn, ast.Name):
            got = mm.module.functions.get(tail)
            if got is not None:
                out.append(got)
            return out
        if not isinstance(fn, ast.Attribute):
            return out
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id == "self" and site.cls:
            cm = mm.classes.get(site.cls)
            if cm and tail in cm.methods:
                out.append(cm.methods[tail])
            return out
        # typed receiver (handle._finish(), self.frontend.submit(), ...)
        if site.recv_type and site.recv_type in self.class_index:
            _, tcm = self.class_index[site.recv_type]
            if tail in tcm.methods:
                out.append(tcm.methods[tail])
        return out

    def _build_graph(self) -> None:
        for mm in self.models:
            rel = mm.module.rel
            for acq in mm.acquisitions:
                if acq.held_before:
                    self._add_edge(acq.held_before[-1], acq.lock, rel,
                                   getattr(acq.node, "lineno", 1))
            for site in mm.calls:
                if not site.held:
                    continue
                for callee in self._callees(mm, site):
                    for acq in self.func_acqs.get(id(callee), ()):
                        if not acq.held_before:   # callee's own top level
                            self._add_edge(
                                site.held[-1], acq.lock, rel,
                                getattr(site.node, "lineno", 1))

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock-order graph (one per SCC with
        ≥2 nodes or a self-loop), as lock-id lists."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in graph.get(v, ()):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return sccs


def build_project_graph(paths: Sequence[str]
                        ) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """The static LK003 edge set for ``paths`` — the reference the
    TracedLock runtime cross-check compares observed order against.

    Relative paths that don't exist under the caller's cwd resolve
    against the repo root: a silently-empty graph would invert the
    cross-check's contract (observed ⊆ static) into a vacuous pass
    of its converse."""
    root = core.repo_root()
    resolved = [p if os.path.isabs(p) or os.path.exists(p)
                else os.path.join(root, p) for p in paths]
    missing = [p for p in resolved if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"build_project_graph: no such path(s): {missing}")
    modules = [m for m in (core.load_module(f)
                           for f in core.collect_files(resolved)) if m]
    return ProjectModel(modules).edges
