"""LK001 — shared mutable attribute written from ≥2 thread roles with
no common lock.

The bug class behind most of the hand-found serving races: an instance
attribute that both a background thread (driver, worker, housekeeper)
and an externally-driven caller write, with no lock covering both
sides.  Under the GIL a single reference store is atomic, but
read-modify-write sequences (``+=``, swap-and-clear, flag check →
assign) interleave freely — the exact shape of the lost-exception race
the device prefetcher shipped with (fixed in this PR, regression test
in tests/test_locklint.py).

Writes inside ``__init__`` are construction-time (happens-before
publication) and don't count.  The finalizer role is discounted here:
``__del__`` ordering hazards are LK005's domain, and counting it would
flag every ``__del__ → close()`` teardown path twice.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import core
from . import model

_SETUP = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


@core.register
class SharedStateRule(core.Rule):
    id = "LK001"
    name = "unlocked-shared-state"
    severity = "error"
    doc = ("instance attribute written from two or more thread roles "
           "with no lock held in common across the write sites")
    hint = ("guard every write with one lock (the owning object's), or "
            "confine the attribute to a single thread role; suppress "
            "with '# locklint: disable=LK001' + justification if the "
            "writes are provably ordered another way")

    def check(self, module: core.Module):
        mm = model.get_model(module)
        grouped: Dict[Tuple[str, str], List[model.WriteSite]] = {}
        for w in mm.writes:
            if w.attr.isupper():
                continue
            fname = getattr(w.func, "name", "") if w.func is not None else ""
            if fname in _SETUP:
                continue
            grouped.setdefault((w.cls, w.attr), []).append(w)
        for (cls, attr), sites in sorted(grouped.items()):
            roles = set()
            lock_sets = []
            witnesses = []
            for s in sites:
                site_roles = mm.roles_of(s.func) - {model.ROLE_FINALIZER}
                if not site_roles:
                    continue                  # finalizer-only path
                roles |= site_roles
                lock_sets.append({ref.id for ref in s.held})
                witnesses.append(s)
            if len(roles) < 2 or not witnesses:
                continue
            if set.intersection(*lock_sets):
                continue                      # one lock covers every write
            first = min(witnesses, key=lambda s: getattr(s.node, "lineno", 1))
            yield self.finding(
                module, first.node,
                f"'{cls}.{attr}' is written from thread roles "
                f"{{{', '.join(sorted(roles))}}} with no common lock "
                f"({len(witnesses)} write sites)")
