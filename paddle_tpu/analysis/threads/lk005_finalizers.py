"""LK005 — finalizer touching locked state or joining threads.

``__del__`` (and ``atexit`` handlers) run at an arbitrary point in an
arbitrary thread — possibly during interpreter shutdown when module
globals are already torn down, possibly while another thread holds the
very lock the finalizer wants.  A finalizer that acquires locks, joins
threads, or does queue handoff is therefore a shutdown race by
construction.  This formalizes what six TL006-suppressed
``except Exception: pass`` blocks used to stand in for: the sites that
*deliberately* run a best-effort ``close()`` from ``__del__`` now
carry an explicit ``# locklint: disable=LK005`` with a per-site
justification, instead of hiding behind the broad-except suppression.

The walk is transitive through the model's resolved call targets (the
``__del__ → close() → join`` chain), matching how the roles propagate.
"""

from __future__ import annotations

import ast
from typing import Optional, Set

from .. import core
from . import model


def _finalizer_hazard(mm: model.ModuleModel,
                      entry: ast.AST) -> Optional[str]:
    """First hazard reachable from ``entry`` (a finalizer function),
    or None."""
    reached: Set[int] = set()
    frontier = [entry]
    while frontier:
        f = frontier.pop()
        if id(f) in reached:
            continue
        reached.add(id(f))
        frontier.extend(mm.call_targets(id(f)))
    for acq in mm.acquisitions:
        if acq.func is not None and id(acq.func) in reached:
            owner = f"{acq.lock.cls}.{acq.lock.attr}" if acq.lock.cls \
                else acq.lock.attr
            return f"acquires lock '{owner}'"
    for site in mm.calls:
        if site.func is None or id(site.func) not in reached:
            continue
        fn = site.node.func
        tail = core.tail_name(fn)
        if tail not in ("join", "put", "get"):
            continue
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Attribute)
                and isinstance(fn.value.value, ast.Name)
                and fn.value.value.id == "self"):
            continue
        attr = fn.value.attr
        cm = mm.classes.get(site.cls)
        if cm is None:
            continue
        if tail == "join" and attr in cm.thread_attrs:
            return f"joins thread 'self.{attr}'"
        if tail in ("put", "get") and attr in cm.queue_attrs:
            return f"does queue .{tail}() on 'self.{attr}'"
    return None


@core.register
class FinalizerRule(core.Rule):
    id = "LK005"
    name = "finalizer-touches-locked-state"
    severity = "warning"
    doc = ("__del__ / atexit finalizer (transitively) acquires locks, "
           "joins threads, or does queue handoff — a shutdown race: "
           "finalizers run at arbitrary points in arbitrary threads, "
           "possibly after module teardown")
    hint = ("prefer explicit close()/context-manager lifecycles; if "
            "the __del__ is a deliberate best-effort backstop, keep it "
            "idempotent + exception-swallowing and suppress with "
            "'# locklint: disable=LK005' + a per-site justification")

    def check(self, module: core.Module):
        mm = model.get_model(module)
        for cm in mm.classes.values():
            fin = cm.methods.get("__del__")
            if fin is None:
                continue
            hazard = _finalizer_hazard(mm, fin)
            if hazard:
                yield self.finding(
                    module, fin,
                    f"'{cm.name}.__del__' {hazard} — finalizers race "
                    f"interpreter shutdown and every other thread")
        for name in sorted(mm.atexit_targets):
            fn = mm.module.functions.get(name)
            if fn is None:
                continue
            hazard = _finalizer_hazard(mm, fn)
            if hazard:
                yield self.finding(
                    module, fn,
                    f"atexit handler '{name}' {hazard} — atexit runs "
                    f"during interpreter shutdown")
