"""Static analysis: tracelint (TL — trace safety for jit/shard_map/
donation code) + kernellint (KL — Pallas-kernel safety on the shared
VMEM cost model in ``analysis/kernel/cost.py``) + locklint (LK —
thread/lock safety on the thread-role model in
``analysis/threads/model.py``).

``python -m paddle_tpu.analysis`` runs all three; ``--select KL`` is
the kernel lane, ``--select LK`` the concurrency lane.  Rule
catalogues in ``docs/static_analysis.md``; committed debt ledgers in
TRACELINT.md / KERNELLINT.md / LOCKLINT.md (all empty).
"""

from .core import (Finding, Module, Rule, all_rules, collect_files,
                   load_module, register, repo_root, run)

__all__ = ["Finding", "Module", "Rule", "all_rules", "collect_files",
           "load_module", "register", "repo_root", "run"]
