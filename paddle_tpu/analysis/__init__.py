"""Static analysis: tracelint (TL — trace safety for jit/shard_map/
donation code) + kernellint (KL — Pallas-kernel safety on the shared
VMEM cost model in ``analysis/kernel/cost.py``).

``python -m paddle_tpu.analysis`` runs both; ``--select KL`` is the
kernel lane.  Rule catalogues in ``docs/static_analysis.md``;
committed debt ledgers in TRACELINT.md / KERNELLINT.md (both empty).
"""

from .core import (Finding, Module, Rule, all_rules, collect_files,
                   load_module, register, repo_root, run)

__all__ = ["Finding", "Module", "Rule", "all_rules", "collect_files",
           "load_module", "register", "repo_root", "run"]
