"""tracelint — trace-safety static analysis for jit/shard_map/donation
code (``python -m paddle_tpu.analysis``; rule catalogue in
``docs/static_analysis.md``; committed debt ledger in TRACELINT.md).
"""

from .core import (Finding, Module, Rule, all_rules, collect_files,
                   load_module, register, repo_root, run)

__all__ = ["Finding", "Module", "Rule", "all_rules", "collect_files",
           "load_module", "register", "repo_root", "run"]
