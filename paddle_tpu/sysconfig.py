"""paddle.sysconfig parity (reference python/paddle/sysconfig.py):
include/lib dirs for the custom-op toolchain (utils.cpp_extension
consumes these)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    return os.path.join(_ROOT, "native", "include")


def get_lib() -> str:
    return os.path.join(_ROOT, "native")
