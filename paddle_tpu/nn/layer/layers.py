"""``Layer`` — the module base class.

Analog of the reference's ``paddle.nn.Layer``
(/root/reference/python/paddle/nn/layer/layers.py:353): parameter/buffer/
sublayer registries, hooks, ``state_dict``/``set_state_dict``, train/eval,
``to``.  TPU-native addition: the *functional bridge*
(:func:`state_arrays` / :func:`functional_state` / :func:`functional_call`)
— a Layer's parameters form a pytree of ``jax.Array``s that can be swapped
for traced values, so one imperative module definition serves both eager
execution and whole-graph ``jax.jit`` (the reference needed dy2static/SOT
bytecode translation for this; here it is a value swap).
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core import dtypes as _dt
from ...core.tensor import Parameter, Tensor
from ..attr import ParamAttr
from .. import initializer as I

__all__ = ["Layer", "state_arrays", "functional_state", "functional_call",
           "functional_call_with_buffers"]


class HookRemoveHelper:
    def __init__(self, hooks: dict, key: int):
        self._hooks = hooks
        self._key = key

    def remove(self) -> None:
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self.training = True
        self._dtype = _dt.canonical_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self._hook_id = 0

    # ------------------------------------------------------------------
    # attribute routing
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            self._sub_layers.pop(name, None)
            self._buffers.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            self._parameters.pop(name, None)
            self._buffers.pop(name, None)
            self._sub_layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                    object.__setattr__(self, name, value)
                    return
                raise TypeError(
                    f"cannot assign non-Parameter to parameter slot {name!r}")
            if (self.__dict__.get("_buffers") is not None
                    and name in self._buffers):
                self._buffers[name] = (value if isinstance(value, Tensor)
                                       else Tensor(value))
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        """Mirror of Layer.create_parameter (layers.py:353 area): initializer
        precedence attr.initializer > default_initializer > (bias→zeros,
        weight→Xavier-uniform like the reference's defaults)."""
        dtype = _dt.canonical_dtype(dtype) or self._dtype
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif I.get_global_initializer(is_bias) is not None:
            init = I.get_global_initializer(is_bias)
        elif is_bias:
            init = I.Constant(0.0)
        else:
            init = I.XavierUniform()
        value = init(tuple(int(s) for s in shape), dtype)
        name = attr.name if attr is not None and attr.name else None
        p = Parameter(value, name=name,
                      trainable=(attr.trainable if attr is not None else True))
        if attr is not None:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, l
            yield from l.named_sublayers(p, include_self=False)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, include_sublayers: bool = True,
                   structured_name_prefix: str = "",
                   use_hook: bool = True) -> "OrderedDict[str, Tensor]":
        out: "OrderedDict[str, Tensor]" = OrderedDict()
        for n, p in self.named_parameters(structured_name_prefix,
                                          include_sublayers):
            out[n] = p
        skip = self._all_non_persistable_buffer_names(structured_name_prefix)
        for n, b in self.named_buffers(structured_name_prefix,
                                       include_sublayers):
            if n not in skip:
                out[n] = b
        return out

    def _all_non_persistable_buffer_names(self, prefix: str = "") -> set:
        names = {f"{prefix}.{n}" if prefix else n
                 for n in self._non_persistable_buffer_names}
        for lname, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = f"{prefix}.{lname}" if prefix else lname
            names |= layer._all_non_persistable_buffer_names(p)
        return names

    def set_state_dict(self, state_dict: Dict[str, Any]) -> None:
        own = self.state_dict()
        missing = []
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                v = src._value if isinstance(src, Tensor) else jnp.asarray(src)
                if tuple(v.shape) != tuple(target.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: checkpoint "
                        f"{tuple(v.shape)} vs layer {tuple(target.shape)}")
                target._value = jnp.asarray(v, target.dtype)
            else:
                missing.append(name)
        if missing:
            import warnings
            warnings.warn(f"state_dict missing keys: {missing[:8]}"
                          + ("..." if len(missing) > 8 else ""))

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # modes / movement
    # ------------------------------------------------------------------
    def train(self) -> "Layer":
        for _, l in self.named_sublayers(include_self=True):
            l.training = True
        return self

    def eval(self) -> "Layer":
        for _, l in self.named_sublayers(include_self=True):
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        if dtype is not None:
            dtype = _dt.canonical_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p._value = jnp.asarray(p._value, dtype)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                    b._value = jnp.asarray(b._value, dtype)
        if device is not None:
            import jax
            from ...core.device import Place
            if isinstance(device, str):
                ty, _, idx = device.partition(":")
                device = Place(ty, int(idx or 0))
            for t in list(self.parameters()) + list(self.buffers()):
                if t is not None:
                    t._value = jax.device_put(t._value, device.jax_device())
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self) -> "Layer":
        return self.to(dtype="float32")

    def bfloat16(self) -> "Layer":
        return self.to(dtype="bfloat16")

    def half(self) -> "Layer":
        return self.to(dtype="float16")

    # ------------------------------------------------------------------
    # call / hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement forward()")

    def extra_repr(self) -> str:
        return ""

    def __repr__(self) -> str:
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            body = repr(l).split("\n")
            body = [body[0]] + ["  " + b for b in body[1:]]
            lines.append(f"  ({name}): " + "\n".join(body))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self) -> str:
        return self._name_scope

    def clear_gradients(self) -> None:
        for p in self.parameters():
            p.clear_grad()


# ---------------------------------------------------------------------------
# Functional bridge — the eager↔jit pivot
# ---------------------------------------------------------------------------
def state_arrays(layer: Layer, trainable_only: bool = False) -> Dict[str, Any]:
    """Extract {name: jax.Array} for all params (and buffers unless
    trainable_only).  The result is a pytree suitable for jax transforms."""
    out = {}
    for n, p in layer.named_parameters():
        if not trainable_only or p.trainable:
            out[n] = p._value
    if not trainable_only:
        for n, b in layer.named_buffers():
            if b is not None and n not in out:
                out[n] = b._value
    return out


@contextlib.contextmanager
def functional_state(layer: Layer, arrays: Dict[str, Any]):
    """Temporarily swap the layer's parameter/buffer values for ``arrays``
    (possibly traced).  Restores originals on exit."""
    slots: Dict[str, Tensor] = {}
    for n, p in layer.named_parameters():
        slots[n] = p
    for n, b in layer.named_buffers():
        if b is not None and n not in slots:
            slots[n] = b
    saved = {}
    try:
        for n, v in arrays.items():
            if n in slots:
                saved[n] = slots[n]._value
                slots[n]._value = v
        yield layer
    finally:
        for n, v in saved.items():
            slots[n]._value = v


def functional_call(layer: Layer, arrays: Dict[str, Any], *args,
                    rng=None, **kwargs):
    """Run ``layer(*args)`` with parameters/buffers taken from ``arrays`` —
    pure w.r.t. ``arrays`` and usable under jax.jit/grad/shard_map."""
    from ...core.rng import rng_scope
    ctx = rng_scope(rng) if rng is not None else contextlib.nullcontext()
    with functional_state(layer, arrays):
        with ctx:
            return layer(*args, **kwargs)


def functional_call_with_buffers(layer: Layer, arrays: Dict[str, Any], *args,
                                 rng=None, **kwargs):
    """Like :func:`functional_call`, but also returns the post-forward buffer
    values (e.g. BatchNorm running stats updated during the call) so jitted
    train steps can thread mutable state through as explicit pytrees."""
    from ...core.rng import rng_scope
    ctx = rng_scope(rng) if rng is not None else contextlib.nullcontext()
    with functional_state(layer, arrays):
        with ctx:
            out = layer(*args, **kwargs)
        new_buffers = {}
        for n, b in layer.named_buffers():
            if b is not None:
                new_buffers[n] = b._value
    return out, new_buffers
