"""Recurrent layers (reference python/paddle/nn/layer/rnn.py —
RNNCellBase:224, SimpleRNNCell:322, LSTMCell:473, GRUCell:663, RNN:820,
BiRNN:938, SimpleRNN/LSTM/GRU multi-layer classes).

TPU-first: the whole time recurrence is ONE taped op built on
``jax.lax.scan`` (no Python-per-timestep dispatch — the XLA analog of the
reference's cudnn fused RNN kernels), with optional sequence-length masking
and bidirectional stacking.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from .. import initializer as I
from ..attr import ParamAttr
from .common import Dropout
from .container import LayerList
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


# ---------------------------------------------------------------------------
# pure scan kernels (one taped op per direction per layer)
# ---------------------------------------------------------------------------
def _mask_step(new, old, t, seq_len):
    """Keep `new` while t < seq_len else carry `old` (per batch row)."""
    if seq_len is None:
        return new
    keep = (t < seq_len)[:, None]
    return jnp.where(keep, new, old)


def _simple_rnn_scan(x, h0, w_ih, w_hh, b_ih, b_hh, seq_len=None,
                     *, activation="tanh", reverse=False):
    """x: [T, B, I] time-major; h0: [B, H] -> (outputs [T, B, H], h_n)."""
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    T = x.shape[0]
    # precompute input projections in one big matmul (MXU-friendly)
    xp = jnp.einsum("tbi,hi->tbh", x, w_ih) + b_ih

    def body(h, inp):
        t, xpt = inp
        h_new = act(xpt + h @ w_hh.T + b_hh)
        h2 = _mask_step(h_new, h, t, seq_len)
        return h2, h2

    ts = jnp.arange(T) if not reverse else jnp.arange(T - 1, -1, -1)
    xs = xp if not reverse else xp[::-1]
    h_n, ys = jax.lax.scan(body, h0, (ts, xs))
    if reverse:
        ys = ys[::-1]
    return ys, h_n


def _lstm_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_len=None,
               *, reverse=False):
    """Gates ordered [i, f, g(cell), o] like the reference."""
    T, B, _ = x.shape
    H = h0.shape[-1]
    xp = jnp.einsum("tbi,gi->tbg", x, w_ih) + b_ih

    def body(carry, inp):
        h, c = carry
        t, xpt = inp
        gates = xpt + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        h2 = _mask_step(h_new, h, t, seq_len)
        c2 = _mask_step(c_new, c, t, seq_len)
        return (h2, c2), h2

    ts = jnp.arange(T) if not reverse else jnp.arange(T - 1, -1, -1)
    xs = xp if not reverse else xp[::-1]
    (h_n, c_n), ys = jax.lax.scan(body, (h0, c0), (ts, xs))
    if reverse:
        ys = ys[::-1]
    return ys, h_n, c_n


def _gru_scan(x, h0, w_ih, w_hh, b_ih, b_hh, seq_len=None, *, reverse=False):
    """Gates ordered [r, z, c] (reset, update, candidate) like the
    reference GRUCell."""
    T = x.shape[0]
    xp = jnp.einsum("tbi,gi->tbg", x, w_ih) + b_ih

    def body(h, inp):
        t, xpt = inp
        hp = h @ w_hh.T + b_hh
        xr, xz, xc = jnp.split(xpt, 3, axis=-1)
        hr, hz, hc = jnp.split(hp, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        h_new = (1 - z) * c + z * h
        h2 = _mask_step(h_new, h, t, seq_len)
        return h2, h2

    ts = jnp.arange(T) if not reverse else jnp.arange(T - 1, -1, -1)
    xs = xp if not reverse else xp[::-1]
    h_n, ys = jax.lax.scan(body, h0, (ts, xs))
    if reverse:
        ys = ys[::-1]
    return ys, h_n


_simple_rnn_op = primitive("rnn_scan")(_simple_rnn_scan)
_lstm_op = primitive("lstm_scan")(_lstm_scan)
_gru_op = primitive("gru_scan")(_gru_scan)


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------
class RNNCellBase(Layer):
    """Base: weight creation + single-step `forward(inputs, states)`
    (reference rnn.py:224)."""

    def __init__(self, input_size: int, hidden_size: int, gates: int,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        G = gates * hidden_size
        self.weight_ih = self.create_parameter(
            [G, input_size], attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter(
            [G, hidden_size], attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter(
            [G], attr=bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter(
            [G], attr=bias_hh_attr, is_bias=True, default_initializer=init)

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        b = batch_ref.shape[0]
        return Tensor(jnp.full((b, self.hidden_size),
                               init_value, jnp.float32))

    @property
    def state_shape(self):
        return (self.hidden_size,)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        ys, h_n = _simple_rnn_op(
            inputs.unsqueeze(0), states, self.weight_ih, self.weight_hh,
            self.bias_ih, self.bias_hh, activation=self.activation)
        out = ys.squeeze(0)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 4, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = (self.get_initial_states(inputs),
                      self.get_initial_states(inputs))
        h, c = states
        ys, h_n, c_n = _lstm_op(inputs.unsqueeze(0), h, c, self.weight_ih,
                                self.weight_hh, self.bias_ih, self.bias_hh)
        return ys.squeeze(0), (h_n, c_n)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 3, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        ys, h_n = _gru_op(inputs.unsqueeze(0), states, self.weight_ih,
                          self.weight_hh, self.bias_ih, self.bias_hh)
        out = ys.squeeze(0)
        return out, out


# ---------------------------------------------------------------------------
# sequence wrappers
# ---------------------------------------------------------------------------
def _run_cell_over_time(cell, x_tm, h0, seq_len, reverse):
    """Dispatch the right scan op for a cell. x_tm: [T,B,I] Tensor."""
    if isinstance(cell, LSTMCell):
        h, c = h0
        ys, h_n, c_n = _lstm_op(x_tm, h, c, cell.weight_ih, cell.weight_hh,
                                cell.bias_ih, cell.bias_hh, seq_len,
                                reverse=reverse)
        return ys, (h_n, c_n)
    if isinstance(cell, GRUCell):
        ys, h_n = _gru_op(x_tm, h0, cell.weight_ih, cell.weight_hh,
                          cell.bias_ih, cell.bias_hh, seq_len,
                          reverse=reverse)
        return ys, h_n
    ys, h_n = _simple_rnn_op(x_tm, h0, cell.weight_ih, cell.weight_hh,
                             cell.bias_ih, cell.bias_hh, seq_len,
                             activation=cell.activation, reverse=reverse)
    return ys, h_n


def _default_state(cell, x_tm):
    b = x_tm.shape[1]
    zero = Tensor(jnp.zeros((b, cell.hidden_size), jnp.float32))
    if isinstance(cell, LSTMCell):
        return (zero, Tensor(jnp.zeros((b, cell.hidden_size), jnp.float32)))
    return zero


class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py:820)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        if initial_states is None:
            initial_states = _default_state(self.cell, x)
        ys, final = _run_cell_over_time(self.cell, x, initial_states,
                                        sequence_length, self.is_reverse)
        if not self.time_major:
            ys = ys.transpose([1, 0, 2])
        return ys, final


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference
    rnn.py:938)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import api as _api
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        if initial_states is None:
            s_fw = _default_state(self.cell_fw, x)
            s_bw = _default_state(self.cell_bw, x)
        else:
            s_fw, s_bw = initial_states
        y_fw, f_fw = _run_cell_over_time(self.cell_fw, x, s_fw,
                                         sequence_length, False)
        y_bw, f_bw = _run_cell_over_time(self.cell_bw, x, s_bw,
                                         sequence_length, True)
        ys = _api.concat([y_fw, y_bw], axis=-1)
        if not self.time_major:
            ys = ys.transpose([1, 0, 2])
        return ys, (f_fw, f_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent network."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.mode = mode
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.time_major = time_major
        self.dropout_p = dropout
        self.dropout = Dropout(dropout) if dropout > 0 else None
        self.hidden_size = hidden_size
        num_dir = 2 if self.bidirectional else 1

        def make_cell(in_size):
            kw = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(in_size, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_size, hidden_size, **kw)
            return SimpleRNNCell(in_size, hidden_size,
                                 activation=activation, **kw)

        cells = []
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * num_dir
            cells.append(make_cell(in_size))
            if self.bidirectional:
                cells.append(make_cell(in_size))
        self.cells = LayerList(cells)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import api as _api
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        num_dir = 2 if self.bidirectional else 1
        finals = []
        for layer in range(self.num_layers):
            cell_fw = self.cells[layer * num_dir]
            s_fw = self._pick_state(initial_states, layer * num_dir, x,
                                    cell_fw)
            y_fw, f_fw = _run_cell_over_time(cell_fw, x, s_fw,
                                             sequence_length, False)
            if self.bidirectional:
                cell_bw = self.cells[layer * num_dir + 1]
                s_bw = self._pick_state(initial_states,
                                        layer * num_dir + 1, x, cell_bw)
                y_bw, f_bw = _run_cell_over_time(cell_bw, x, s_bw,
                                                 sequence_length, True)
                x = _api.concat([y_fw, y_bw], axis=-1)
                finals.extend([f_fw, f_bw])
            else:
                x = y_fw
                finals.append(f_fw)
            if self.dropout is not None and layer != self.num_layers - 1:
                x = self.dropout(x)
        outputs = x if self.time_major else x.transpose([1, 0, 2])
        final_states = self._stack_finals(finals)
        return outputs, final_states

    def _pick_state(self, initial_states, idx, x_tm, cell):
        if initial_states is None:
            return _default_state(cell, x_tm)
        if self.mode == "LSTM":
            h, c = initial_states
            return (h[idx], c[idx])
        return initial_states[idx]

    def _stack_finals(self, finals):
        from ...ops import api as _api
        if self.mode == "LSTM":
            hs = _api.stack([f[0] for f in finals], axis=0)
            cs = _api.stack([f[1] for f in finals], axis=0)
            return (hs, cs)
        return _api.stack(finals, axis=0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
