"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "LogSigmoid", "Tanh",
           "Tanhshrink", "Hardshrink", "Hardsigmoid", "Hardswish", "Hardtanh",
           "LeakyReLU", "ELU", "SELU", "CELU", "PReLU", "RReLU", "Silu",
           "Swish", "Mish", "Softmax", "LogSoftmax", "Softmin", "Softplus",
           "Softshrink", "Softsign", "ThresholdedReLU", "Maxout", "GLU",
           "Softmax2D"]


def _simple(name, fn_name, **defaults):
    def forward(self, x):
        fn = getattr(F, fn_name)
        return fn(x, **self._kw)

    def __init__(self, *args, name=None, **kw):
        Layer.__init__(self)
        merged = dict(defaults)
        keys = list(defaults)
        for i, a in enumerate(args):
            merged[keys[i]] = a
        merged.update({k: v for k, v in kw.items() if k in merged})
        self._kw = merged

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
GELU = _simple("GELU", "gelu", approximate=False)
Sigmoid = _simple("Sigmoid", "sigmoid")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Hardshrink = _simple("Hardshrink", "hardshrink", threshold=0.5)
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", min=-1.0, max=1.0)
LeakyReLU = _simple("LeakyReLU", "leaky_relu", negative_slope=0.01)
ELU = _simple("ELU", "elu", alpha=1.0)
SELU = _simple("SELU", "selu")
CELU = _simple("CELU", "celu", alpha=1.0)
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Softmax = _simple("Softmax", "softmax", axis=-1)
LogSoftmax = _simple("LogSoftmax", "log_softmax", axis=-1)
Softmin = _simple("Softmin", "softmin", axis=-1)
Softplus = _simple("Softplus", "softplus", beta=1.0, threshold=20.0)
Softshrink = _simple("Softshrink", "softshrink", threshold=0.5)
Softsign = _simple("Softsign", "softsign")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu",
                          threshold=1.0, value=0.0)
GLU = _simple("GLU", "glu", axis=-1)


class Softmax2D(Layer):
    def forward(self, x):
        return F.softmax(x, axis=-3)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
