"""Layer-class tail (reference python/paddle/nn/__init__.py — the last 26
classes to full name parity): pad layers, unpool/fractional/LP pools,
remaining losses, Unflatten, FeatureAlphaDropout, AdaptiveLogSoftmaxWithLoss,
BeamSearchDecoder.  All are thin Layer wrappers over existing kernels."""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op
from ...core.tensor import Tensor
from .. import functional as F
from .layers import Layer

__all__ = [
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad1D", "ZeroPad2D", "ZeroPad3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "LPPool1D", "LPPool2D",
    "FractionalMaxPool2D", "FractionalMaxPool3D", "Unflatten",
    "FeatureAlphaDropout", "SoftMarginLoss", "MultiMarginLoss",
    "MultiLabelSoftMarginLoss", "GaussianNLLLoss", "PoissonNLLLoss",
    "TripletMarginWithDistanceLoss", "CTCLoss", "RNNTLoss", "HSigmoidLoss",
    "AdaptiveLogSoftmaxWithLoss", "BeamSearchDecoder",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else x


# ------------------------------------------------------------------- pads
class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, n=2):
        super().__init__()
        self.padding = ([padding] * (2 * n) if isinstance(padding, int)
                        else list(padding))
        self.mode = mode
        self.value = value
        self.n = n
        self.data_format = data_format

    def forward(self, x):
        from ...ops import api
        if self.n == 3:
            return api.pad3d(x, self.padding, self.mode, self.value,
                             self.data_format or "NCDHW")
        return api.pad(x, self.padding, mode=self.mode, value=self.value,
                       data_format=self.data_format
                       or ("NCL" if self.n == 1 else "NCHW"))


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format, n=1)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format, n=2)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, n=3)


class ZeroPad1D(Pad1D):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(Pad3D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


# ------------------------------------------------------------------ pools
class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        from ...ops import api
        return api.unpool(x, indices, self.kernel_size, self.stride,
                          self.padding, self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        from ...ops import api
        xv, iv = _v(x), _v(indices)
        x4 = jnp.expand_dims(jnp.asarray(xv), 2)      # [N, C, 1, L]
        i4 = jnp.expand_dims(jnp.asarray(iv), 2)
        if self.output_size is None:
            osz = None
        else:
            o = self.output_size
            osz = (1, o if isinstance(o, int) else o[-1])
        out = api.unpool(Tensor(x4), Tensor(i4), (1, self.kernel_size),
                         (1, self.stride), (0, self.padding), osz)
        return Tensor(jnp.squeeze(_v(out), 2))


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        from ...ops import api
        return api.unpool3d(x, indices, self.kernel_size, self.stride,
                            self.padding, self.output_size)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        from ...ops import api
        return api.lp_pool2d(x, *self.args)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        from ...ops import api
        x4 = jnp.expand_dims(jnp.asarray(_v(x)), 2)
        out = api.lp_pool2d(Tensor(x4), self.norm_type,
                            (1, self.kernel_size),
                            (1, self.stride or self.kernel_size),
                            (0, self.padding), self.ceil_mode)
        return Tensor(jnp.squeeze(_v(out), 2))


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        from ...ops import api
        return api.fractional_max_pool2d(x, *self.args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        from ...ops import api
        return api.fractional_max_pool3d(x, *self.args)


# ------------------------------------------------------------------- misc
class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = tuple(shape)

    def forward(self, x):
        xv = jnp.asarray(_v(x))
        ax = self.axis % xv.ndim
        new = xv.shape[:ax] + self.shape + xv.shape[ax + 1:]
        from ...ops import api
        return api.reshape(x, new)


class FeatureAlphaDropout(Layer):
    """Alpha dropout that drops whole channels (reference
    FeatureAlphaDropout; SELU-preserving statistics)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        from ...core.rng import next_rng_key
        alpha = -1.7580993408473766

        def impl(xv, key):
            shape = (xv.shape[0], xv.shape[1]) + (1,) * (xv.ndim - 2)
            keep = jax.random.bernoulli(key, 1.0 - self.p, shape)
            a = (1.0 / math.sqrt((1 - self.p)
                                 * (1 + self.p * alpha ** 2))) \
                if self.p < 1 else 0.0
            b = -a * alpha * self.p
            return jnp.where(keep, xv, alpha) * a + b

        return run_op("feature_alpha_dropout", impl,
                      (x, next_rng_key()), {})


# ------------------------------------------------------------------ losses
class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.a = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.a)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.a = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive,
                                                   negative, *self.a)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.a = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           *self.a)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over a complete binary tree (reference
    nn.HSigmoidLoss; kernel in ops/impl/nn_ops.py:hsigmoid_loss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter((num_classes - 1, feature_size))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_classes - 1,), is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        from ...ops import api
        return api.hsigmoid_loss(input, label, self.weight, self.bias,
                                 num_classes=self.num_classes,
                                 path_table=path_table,
                                 path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax (reference AdaptiveLogSoftmaxWithLoss, Grave et
    al. arXiv:1609.04309): frequent head classes scored directly, tail
    clusters through down-projected tails."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.in_features = in_features
        self.n_classes = n_classes
        self.head_weight = self.create_parameter(
            (in_features, self.head_size))
        self.head_bias = (self.create_parameter((self.head_size,),
                                                is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter((in_features, hsz))
            w2 = self.create_parameter((hsz, osz))
            self.tail_weights.append((w1, w2))
            setattr(self, f"tail_{i}_proj", w1)
            setattr(self, f"tail_{i}_out", w2)

    def _full_log_prob(self, x):
        xv = jnp.asarray(_v(x))
        head = xv @ jnp.asarray(_v(self.head_weight))
        if self.head_bias is not None:
            head = head + jnp.asarray(_v(self.head_bias))
        head_lp = jax.nn.log_softmax(head, axis=-1)
        parts = [head_lp[:, :self.cutoffs[0]]]
        for i, (w1, w2) in enumerate(self.tail_weights):
            tail = (xv @ jnp.asarray(_v(w1))) @ jnp.asarray(_v(w2))
            tail_lp = jax.nn.log_softmax(tail, axis=-1)
            parts.append(tail_lp
                         + head_lp[:, self.cutoffs[0] + i][:, None])
        return jnp.concatenate(parts, axis=1)

    def forward(self, input, label):
        # adaptive path: score the head once plus each tail cluster's
        # [B, cluster] block — never materialize [B, n_classes]
        xv = jnp.asarray(_v(input))
        lab = jnp.asarray(_v(label)).reshape(-1)
        head = xv @ jnp.asarray(_v(self.head_weight))
        if self.head_bias is not None:
            head = head + jnp.asarray(_v(self.head_bias))
        head_lp = jax.nn.log_softmax(head, axis=-1)
        in_head = lab < self.cutoffs[0]
        out = jnp.take_along_axis(
            head_lp, jnp.where(in_head, lab, 0)[:, None], axis=1)[:, 0]
        out = jnp.where(in_head, out, 0.0)
        for i, (w1, w2) in enumerate(self.tail_weights):
            lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
            hit = (lab >= lo) & (lab < hi)
            tail = (xv @ jnp.asarray(_v(w1))) @ jnp.asarray(_v(w2))
            tail_lp = jax.nn.log_softmax(tail, axis=-1)
            tgt = jnp.take_along_axis(
                tail_lp, jnp.where(hit, lab - lo, 0)[:, None], axis=1)[:, 0]
            cluster_lp = head_lp[:, self.cutoffs[0] + i]
            out = out + jnp.where(hit, tgt + cluster_lp, 0.0)
        return Tensor(out), Tensor(-out.mean())

    def log_prob(self, input):
        return Tensor(self._full_log_prob(input))

    def predict(self, input):
        return Tensor(jnp.argmax(self._full_log_prob(input), axis=-1))


class BeamSearchDecoder:
    """Beam-search decoding driver over an RNN cell (reference
    nn.BeamSearchDecoder + dynamic_decode).  Host-side loop using the
    beam_search op per step — decode is a serving path, not a compiled
    training step."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def decode(self, init_state, max_steps=32):
        from ...ops import api
        W = self.beam_size
        tok = np.full((W, 1), self.start_token, np.int64)
        scores = np.zeros((W,), np.float32)
        scores[1:] = -1e9                  # all beams start identical
        state = jax.tree.map(
            lambda s: jnp.repeat(jnp.asarray(_v(s)), W, axis=0), init_state)
        seq = [tok.copy()]
        for _ in range(max_steps):
            inp = (self.embedding_fn(Tensor(jnp.asarray(tok[:, 0])))
                   if self.embedding_fn else
                   Tensor(jnp.asarray(tok[:, 0], jnp.float32)[:, None]))
            out, state = self.cell(inp, state)
            logits = self.output_fn(out) if self.output_fn else out
            logp = jax.nn.log_softmax(jnp.asarray(_v(logits)), axis=-1)
            K = min(W, logp.shape[-1])
            topv, topi = jax.lax.top_k(logp, K)
            sel, ssc, parent = api.beam_search(
                tok, scores, np.asarray(topi), np.asarray(topv),
                beam_size=W, end_id=self.end_token)
            sel = np.asarray(_v(sel))
            parent = np.asarray(_v(parent)).reshape(-1)
            scores = np.asarray(_v(ssc)).reshape(-1)
            state = jax.tree.map(lambda s: jnp.asarray(_v(s))[parent],
                                 state)
            seq = [s[parent] for s in seq] + [sel]
            tok = sel
            if (sel == self.end_token).all():
                break
        return np.concatenate(seq, axis=1), scores
