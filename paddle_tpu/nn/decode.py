"""Beam-search decoding (reference: python/paddle/nn/decode.py —
``Decoder`` :50, ``BeamSearchDecoder`` :161, ``dynamic_decode`` :1062).

TPU-first design note: the reference keeps two routes (imperative Python
loop + a declarative ``while_loop`` build).  Here decoding state is a pytree
of fixed-shape Tensors, so a single eager loop suffices for parity and the
whole step is jit-compatible: wrap ``decoder.step`` in ``paddle.jit.
to_static`` for a compiled decode step, or drive generation through
``models.generate`` (paged-KV path) for the production route.
"""

from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from ..core.dtypes import index_dtype
from ..core.tensor import Tensor
from ..ops import api as ops
from .. import utils as _nest
from .functional.common import gather_tree


class Decoder:
    """Abstract decode-step interface (reference: nn/decode.py:50)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search on top of an RNN-style cell (reference: nn/decode.py:161).

    The cell maps (inputs [B*W, ...], states) -> (logits [B*W, V], states).
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.kinf = 1e9

    # -- shape helpers ----------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*W, ...] by tiling each batch item W times
        (reference: nn/decode.py:256)."""
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]), stop_gradient=True)

    def _split_batch_beams(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((self.batch_size, self.beam_size)
                                + v.shape[1:]), stop_gradient=True)

    def _merge_batch_beams(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((self.batch_size * self.beam_size,)
                                + v.shape[2:]), stop_gradient=True)

    def _expand_to_beam_size(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(jnp.repeat(v[:, None], self.beam_size, axis=1),
                      stop_gradient=True)

    def _mask_probs(self, probs, finished):
        """Finished beams emit only end_token with log-prob 0 (reference:
        nn/decode.py:344)."""
        noend = jnp.full((probs.shape[-1],), -self.kinf, probs.dtype)
        noend = noend.at[self.end_token].set(0.0)
        fin = finished.astype(bool)[..., None]
        return jnp.where(fin, noend, probs)

    def _gather(self, xv, indices):
        """Per-batch gather along the beam axis (reference:
        nn/decode.py:373)."""
        b = jnp.arange(self.batch_size)[:, None]
        return xv[b, indices]

    # -- Decoder interface ------------------------------------------------
    def initialize(self, initial_cell_states):
        state0 = _nest.flatten(initial_cell_states)[0]
        v0 = state0._value if isinstance(state0, Tensor) else state0
        self.batch_size = int(v0.shape[0])

        cell_states = _nest.map_structure(self._expand_to_beam_size,
                                          initial_cell_states)
        init_inputs = Tensor(jnp.full(
            (self.batch_size, self.beam_size), self.start_token,
            index_dtype()),
            stop_gradient=True)
        row = jnp.asarray([[0.0] + [-self.kinf] * (self.beam_size - 1)],
                          jnp.float32)
        log_probs = Tensor(jnp.tile(row, (self.batch_size, 1)),
                           stop_gradient=True)
        finished = Tensor(jnp.zeros((self.batch_size, self.beam_size), bool),
                          stop_gradient=True)
        lengths = Tensor(jnp.zeros((self.batch_size, self.beam_size),
                                   index_dtype()), stop_gradient=True)
        if self.embedding_fn is not None:
            init_inputs = self.embedding_fn(init_inputs)
        return (init_inputs,
                self.StateWrapper(cell_states, log_probs, finished, lengths),
                finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        lg = logits._value if isinstance(logits, Tensor) else logits
        vocab = lg.shape[-1]
        import jax
        step_lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        step_lp = self._mask_probs(step_lp, beam_state.finished._value)
        log_probs = step_lp + beam_state.log_probs._value[..., None]

        scores = log_probs.reshape(self.batch_size,
                                   self.beam_size * vocab)
        topk_scores, topk_idx = jax.lax.top_k(scores, self.beam_size)
        beam_indices = topk_idx // vocab
        token_indices = (topk_idx % vocab).astype(index_dtype())
        next_log_probs = jnp.take_along_axis(scores, topk_idx, axis=1)

        def regather(x):
            # cell states arrive split as [batch, beam, ...]
            v = x._value if isinstance(x, Tensor) else x
            return Tensor(self._gather(v, beam_indices), stop_gradient=True)

        next_cell_states = _nest.map_structure(regather, next_cell_states)
        fin = self._gather(beam_state.finished._value, beam_indices)
        lens = self._gather(beam_state.lengths._value, beam_indices)
        lens = lens + (~fin).astype(lens.dtype)
        fin = fin | (token_indices == self.end_token)

        out = self.OutputWrapper(
            Tensor(topk_scores, stop_gradient=True),
            Tensor(token_indices, stop_gradient=True),
            Tensor(beam_indices.astype(index_dtype()), stop_gradient=True))
        state = self.StateWrapper(
            next_cell_states,
            Tensor(next_log_probs, stop_gradient=True),
            Tensor(fin, stop_gradient=True),
            Tensor(lens, stop_gradient=True))
        return out, state

    def step(self, time, inputs, states, **kwargs):
        inputs = _nest.map_structure(self._merge_batch_beams, inputs)
        cell_states = _nest.map_structure(self._merge_batch_beams,
                                          states.cell_states)
        cell_outputs, next_cell_states = self.cell(inputs, cell_states,
                                                   **kwargs)
        cell_outputs = _nest.map_structure(self._split_batch_beams,
                                           cell_outputs)
        next_cell_states = _nest.map_structure(self._split_batch_beams,
                                               next_cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)

        out, state = self._beam_search_step(time, cell_outputs,
                                            next_cell_states, states)
        sample_ids = out.predicted_ids
        next_inputs = (self.embedding_fn(sample_ids)
                       if self.embedding_fn else sample_ids)
        return out, state, next_inputs, state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        predicted_ids = gather_tree(outputs.predicted_ids,
                                    outputs.parent_ids)
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder.step`` until all sequences finish or ``max_step_num``
    (reference: nn/decode.py:1062)."""
    initial_inputs, initial_states, initial_finished = \
        decoder.initialize(inits)
    inputs, states = initial_inputs, initial_states
    finished = (initial_finished._value
                if isinstance(initial_finished, Tensor)
                else jnp.asarray(initial_finished))
    step_outputs_acc = None
    time = 0
    limit = int(max_step_num) if max_step_num is not None else 10 ** 9

    seq_lens = jnp.zeros(finished.shape, index_dtype())
    while time < limit:
        t = Tensor(jnp.asarray([time], index_dtype()), stop_gradient=True)
        outputs, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states, **kwargs)
        nf = (next_finished._value if isinstance(next_finished, Tensor)
              else jnp.asarray(next_finished))
        if not decoder.tracks_own_finished:
            nf = nf | finished
        if impute_finished and not decoder.tracks_own_finished:
            def keep_old(new, old):
                nv = new._value if isinstance(new, Tensor) else new
                ov = old._value if isinstance(old, Tensor) else old
                mask = finished.reshape(
                    finished.shape + (1,) * (nv.ndim - finished.ndim))
                return Tensor(jnp.where(mask, ov, nv), stop_gradient=True)
            next_states = _nest.map_structure(keep_old, next_states, states)

        flat = _nest.flatten(outputs)
        if step_outputs_acc is None:
            step_outputs_acc = [[f] for f in flat]
            out_struct = outputs
        else:
            for acc, f in zip(step_outputs_acc, flat):
                acc.append(f)

        if hasattr(next_states, "lengths"):
            seq_lens = next_states.lengths._value
        else:
            seq_lens = seq_lens + (~nf).astype(seq_lens.dtype)

        inputs, states, finished = next_inputs, next_states, nf
        time += 1
        if bool(jnp.all(finished)):
            break

    stacked = [Tensor(jnp.stack([
        (f._value if isinstance(f, Tensor) else jnp.asarray(f))
        for f in acc]), stop_gradient=True) for acc in step_outputs_acc]
    final_outputs = _nest.pack_sequence_as(out_struct, stacked)
    final_states = states

    if hasattr(decoder, "finalize") and type(
            decoder).finalize is not Decoder.finalize:
        final_outputs, final_states = decoder.finalize(
            final_outputs, final_states,
            Tensor(seq_lens, stop_gradient=True))

    if not output_time_major:
        def to_batch_major(x):
            v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
            perm = (1, 0) + tuple(range(2, v.ndim))
            return Tensor(jnp.transpose(v, perm), stop_gradient=True)
        final_outputs = _nest.map_structure(to_batch_major, final_outputs)

    if return_length:
        return final_outputs, final_states, Tensor(seq_lens,
                                                   stop_gradient=True)
    return final_outputs, final_states
