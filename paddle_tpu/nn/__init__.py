"""``paddle_tpu.nn`` (reference: python/paddle/nn/__init__.py)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from . import utils  # noqa: F401
from .attr import ParamAttr  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer, functional_call, functional_call_with_buffers, functional_state, state_arrays  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
