"""Attention functional ops.

Reference: python/paddle/nn/functional/flash_attention.py:976
(``scaled_dot_product_attention``), :195 (``flash_attention``).  The jnp
path here is the numeric reference; when the input is on TPU and shapes
allow, dispatch goes to the Pallas flash-attention kernel
(paddle_tpu.ops.pallas.flash_attention).  Layout follows paddle:
[batch, seq, num_heads, head_dim].
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import run_op
from ...core.rng import next_rng_key


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, key=None,
              scale=None):
    # q/k/v: [B, S, H, D] → compute in [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    logits = logits.astype(jnp.float32)
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        logits = jnp.where(cm, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True):
    # NOTE on backends: the per-op API cannot see how many layers will
    # hold residuals (a 12-layer model calls this once per layer), so the
    # memory-based dense/flash policy (ops/attention_policy) is applied
    # only in the model builders where layer count is known; here flash
    # stays the TPU default — the memory-safe choice.
    use_pallas = _should_use_pallas(query)
    rng = next_rng_key() if (dropout_p > 0.0 and training) else None

    def impl(q, k, v, m, rk):
        no_drop = dropout_p == 0.0 or not training
        if use_pallas and m is None and no_drop:
            from ...ops.pallas.flash_backends import tuned_flash
            return tuned_flash(q, k, v, causal=is_causal)
        # masks stay on the dense path: the kernel's bias input is
        # non-differentiable and only broadcasts on dims 0/1, so routing
        # arbitrary user masks there would silently drop mask gradients
        # or mis-index size-1 seq dims
        return _sdpa_ref(q, k, v, m, dropout_p if training else 0.0,
                         is_causal, rk)

    return run_op("scaled_dot_product_attention", impl,
                  (query, key, value, attn_mask, rng), {})


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Varlen flash attention (reference: flash_attn_unpadded
    nn/functional/flash_attention.py:593).  Packed layout: [total_tokens,
    num_heads, head_dim] with cu_seqlens prefix sums.  Dispatches to the
    Pallas segment-ids kernel (O(T) memory); dense segment-masked attention
    is the off-TPU / dropout fallback."""
    use_pallas = _should_use_pallas(query) and (
        dropout == 0.0 or not training)

    def impl(q, k, v, cq, ck):
        t_q = q.shape[0]
        t_k = k.shape[0]
        seg_q = jnp.searchsorted(cq, jnp.arange(t_q), side="right") - 1
        seg_k = jnp.searchsorted(ck, jnp.arange(t_k), side="right") - 1
        same_packing = t_q == t_k and (
            cu_seqlens_q is cu_seqlens_k or _values_equal(cq, ck))
        if use_pallas and (not causal or same_packing):
            # packed self-attention (identical cu_seqlens): global position
            # order == within-segment order, so kernel-causal + segment
            # mask == per-segment causal.  Differing q/k packings fall back
            # to the dense path, whose causal mask is per-segment-local.
            from ...ops.pallas.flash_backends import tuned_flash as fa
            return fa(q[None], k[None], v[None], scale, causal,
                      segment_ids=seg_q[None].astype(jnp.int32),
                      kv_segment_ids=seg_k[None].astype(jnp.int32))[0]
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(t_q) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(t_k) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits.astype(jnp.float32),
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = run_op("flash_attn_unpadded", impl,
                 (query, key, value, cu_seqlens_q, cu_seqlens_k), {})
    return out, None


def _values_equal(a, b) -> bool:
    """Concrete-value equality for dispatch decisions; False under trace."""
    import numpy as np
    try:
        return a.shape == b.shape and bool(np.array_equal(np.asarray(a),
                                                          np.asarray(b)))
    except Exception:   # traced values — can't decide, stay conservative
        return False


def _interpret_forced() -> bool:
    """Tests force the Pallas interpret path off-TPU; the perf-based
    backend policy must not override that routing."""
    from ...core.flags import FLAGS
    return bool(FLAGS.pallas_interpret)


def _should_use_pallas(query) -> bool:
    from ...core.flags import FLAGS
    try:
        import jax
        dev = jax.devices()[0].platform.lower()
    except Exception:
        return False
    if FLAGS.pallas_interpret:
        return True
    return dev in ("tpu", "axon")


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    from ...core import dtypes as _dt

    def impl(ln):
        m = maxlen or int(jnp.max(ln))
        return (jnp.arange(m)[None, :] < ln[:, None]).astype(
            _dt.canonical_dtype(dtype))

    return run_op("sequence_mask", impl, (lengths,), {}, differentiable=False)


# ---------------------------------------------------------------------------
# round-3 API tail (VERDICT r2 item 5)
# ---------------------------------------------------------------------------

def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Packed-QKV flash attention (reference:
    nn/functional/flash_attention.py:399).  qkv is 5-D
    [batch, seq, nheads/nheads_k + 2, nheads_k, head_dim]; the first
    ``ratio`` slots along dim 2 are query head groups (GQA), the last two
    are K and V."""
    from ...core.dispatch import run_op as _run

    def impl(p):
        b, s, slots, nh_k, hd = p.shape
        ratio = slots - 2
        q = p[:, :, :ratio].reshape(b, s, ratio * nh_k, hd)
        k = p[:, :, ratio]
        v = p[:, :, ratio + 1]
        if ratio > 1:
            # GQA: flattened q head r*nh_k + j reads kv head j -> tile
            k = jnp.tile(k, (1, 1, ratio, 1))
            v = jnp.tile(v, (1, 1, ratio, 1))
        return q, k, v

    q, k, v = _run("qkv_unpack", impl, (qkv,), {})
    out, sm = flash_attention(q, k, v, dropout=dropout, causal=causal,
                              return_softmax=return_softmax,
                              training=training)
    return out, sm


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, *,
                                fixed_seed_offset=None, rng_name="",
                                varlen_padded=True, training=True,
                                name=None):
    """Varlen packed-QKV flash attention (reference:
    nn/functional/flash_attention.py:792).  qkv is 4-D
    [total_tokens, nheads/nheads_k + 2, nheads_k, head_dim]."""
    from ...core.dispatch import run_op as _run

    def impl(p):
        t, slots, nh_k, hd = p.shape
        ratio = slots - 2
        q = p[:, :ratio].reshape(t, ratio * nh_k, hd)
        k = p[:, ratio]
        v = p[:, ratio + 1]
        if ratio > 1:
            k = jnp.tile(k, (1, ratio, 1))
            v = jnp.tile(v, (1, ratio, 1))
        return q, k, v

    q, k, v = _run("qkv_unpack_varlen", impl, (qkv,), {})
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale=scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block/CSR-sparse attention (reference:
    nn/functional/sparse_attention.py:22 → sparse_attention CUDA kernel).

    q/k/v: [batch, num_heads, seq, head_dim]; the CSR pair
    (offset [B,H,L+1], columns [B,H,nnz]) names, per query row, which key
    columns participate.  TPU formulation: scatter the CSR layout into a
    boolean mask and run masked softmax attention — XLA fuses the mask
    into the attention matmuls; the O(L²) dense intermediate matches the
    kernel's numerics exactly and stays MXU-friendly."""

    def impl(q, k, v, off, cols, kpm, am):
        b, h, L, d = q.shape
        nnz = cols.shape[-1]
        # row id of each nnz slot: searchsorted per (b, h)
        def row_ids(o):
            return jnp.searchsorted(o, jnp.arange(nnz), side="right") - 1

        rows = jax.vmap(jax.vmap(row_ids))(off)          # [B,H,nnz]
        mask = jnp.zeros((b, h, L, L), bool)
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        bb = jnp.broadcast_to(bidx, rows.shape)
        hh = jnp.broadcast_to(hidx, rows.shape)
        # slots beyond offset[-1] (padding) scatter to row -1 -> dropped
        valid = rows >= 0
        rows_s = jnp.where(valid, rows, 0)
        cols_s = jnp.where(valid, cols, 0)
        mask = mask.at[bb, hh, rows_s, cols_s].max(valid)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
        neg = jnp.finfo(jnp.float32).min
        logits = jnp.where(mask, logits.astype(jnp.float32), neg)
        if kpm is not None:
            logits = logits + kpm[:, None, None, :].astype(jnp.float32)
        if am is not None:
            logits = logits + am.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        # rows with no nonzeros: zero output (kernel semantics)
        any_row = jnp.any(mask, -1, keepdims=True)
        probs = jnp.where(any_row, probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    return run_op("sparse_attention", impl,
                  (query, key, value, sparse_csr_offset, sparse_csr_columns,
                   key_padding_mask, attn_mask), {})


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=False, return_softmax=False,
                                     return_softmax_lse=False,
                                     return_seed_offset=False,
                                     training=True, name=None):
    """Flash attention with a start-row sparse mask (reference:
    nn/functional/flash_attention.py:1098): for column j, rows
    i >= start_row_indices[b, h, j] are masked out."""

    key_rng = None
    if dropout_p > 0.0 and training:
        from ...core.rng import next_rng_key
        key_rng = next_rng_key()

    def impl(q, k, v, sri, rk):
        b, s, nh, d = q.shape
        rows = jnp.arange(s)
        # sri: [B, H, S] per-column start row
        mask = rows[None, None, :, None] < sri[:, :, None, :]
        if is_causal:
            causal = rows[:, None] >= rows[None, :]
            mask = mask & causal[None, None]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
        neg = jnp.finfo(jnp.float32).min
        logits = jnp.where(mask, logits.astype(jnp.float32), neg)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        # rows with no attendable key: zero output (kernel semantics),
        # not the uniform-softmax artifact
        probs = jnp.where(jnp.any(mask, -1, keepdims=True), probs, 0.0)
        if rk is not None:
            keep = jax.random.bernoulli(rk, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    out = run_op("flash_attention_with_sparse_mask", impl,
                 (query, key, value, attn_mask_start_row_indices, key_rng),
                 {})
    rets = [out]
    if return_softmax:
        rets.append(None)
    if return_softmax_lse:
        rets.append(None)
    if return_seed_offset:
        rets.append(None)
    return tuple(rets) if len(rets) > 1 else out
