"""Attention functional ops.

Reference: python/paddle/nn/functional/flash_attention.py:976
(``scaled_dot_product_attention``), :195 (``flash_attention``).  The jnp
path here is the numeric reference; when the input is on TPU and shapes
allow, dispatch goes to the Pallas flash-attention kernel
(paddle_tpu.ops.pallas.flash_attention).  Layout follows paddle:
[batch, seq, num_heads, head_dim].
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import run_op
from ...core.rng import next_rng_key


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, key=None,
              scale=None):
    # q/k/v: [B, S, H, D] → compute in [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    logits = logits.astype(jnp.float32)
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        logits = jnp.where(cm, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True):
    use_pallas = _should_use_pallas(query)
    rng = next_rng_key() if (dropout_p > 0.0 and training) else None

    def impl(q, k, v, m, rk):
        no_drop = dropout_p == 0.0 or not training
        if use_pallas and m is None and no_drop:
            from ...ops.pallas.flash_attention import flash_attention_fwd
            return flash_attention_fwd(q, k, v, causal=is_causal)
        # masks stay on the dense path: the kernel's bias input is
        # non-differentiable and only broadcasts on dims 0/1, so routing
        # arbitrary user masks there would silently drop mask gradients
        # or mis-index size-1 seq dims
        return _sdpa_ref(q, k, v, m, dropout_p if training else 0.0,
                         is_causal, rk)

    return run_op("scaled_dot_product_attention", impl,
                  (query, key, value, attn_mask, rng), {})


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True):
    """Varlen flash attention (reference: flash_attn_unpadded
    nn/functional/flash_attention.py:593).  Packed layout: [total_tokens,
    num_heads, head_dim] with cu_seqlens prefix sums.  Dispatches to the
    Pallas segment-ids kernel (O(T) memory); dense segment-masked attention
    is the off-TPU / dropout fallback."""
    use_pallas = _should_use_pallas(query) and (
        dropout == 0.0 or not training)

    def impl(q, k, v, cq, ck):
        t_q = q.shape[0]
        t_k = k.shape[0]
        seg_q = jnp.searchsorted(cq, jnp.arange(t_q), side="right") - 1
        seg_k = jnp.searchsorted(ck, jnp.arange(t_k), side="right") - 1
        same_packing = t_q == t_k and (
            cu_seqlens_q is cu_seqlens_k or _values_equal(cq, ck))
        if use_pallas and (not causal or same_packing):
            # packed self-attention (identical cu_seqlens): global position
            # order == within-segment order, so kernel-causal + segment
            # mask == per-segment causal.  Differing q/k packings fall back
            # to the dense path, whose causal mask is per-segment-local.
            from ...ops.pallas.flash_attention import flash_attention as fa
            return fa(q[None], k[None], v[None], scale, causal,
                      segment_ids=seg_q[None].astype(jnp.int32),
                      kv_segment_ids=seg_k[None].astype(jnp.int32))[0]
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(t_q) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(t_k) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits.astype(jnp.float32),
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = run_op("flash_attn_unpadded", impl,
                 (query, key, value, cu_seqlens_q, cu_seqlens_k), {})
    return out, None


def _values_equal(a, b) -> bool:
    """Concrete-value equality for dispatch decisions; False under trace."""
    import numpy as np
    try:
        return a.shape == b.shape and bool(np.array_equal(np.asarray(a),
                                                          np.asarray(b)))
    except Exception:   # traced values — can't decide, stay conservative
        return False


def _should_use_pallas(query) -> bool:
    from ...core.flags import FLAGS
    try:
        import jax
        dev = jax.devices()[0].platform.lower()
    except Exception:
        return False
    if FLAGS.pallas_interpret:
        return True
    return dev in ("tpu", "axon")


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    from ...core import dtypes as _dt

    def impl(ln):
        m = maxlen or int(jnp.max(ln))
        return (jnp.arange(m)[None, :] < ln[:, None]).astype(
            _dt.canonical_dtype(dtype))

    return run_op("sequence_mask", impl, (lengths,), {}, differentiable=False)
