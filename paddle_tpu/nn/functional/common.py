"""Common functional ops: linear, dropout, embedding, interpolate, …
(reference: python/paddle/nn/functional/common.py, input.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op
from ...core.rng import next_rng_key


def linear(x, weight, bias=None):
    """y = x @ W + b with paddle weight layout [in, out] (reference:
    nn/functional/common.py linear → matmul_v2 + elementwise_add; on TPU a
    single MXU matmul with fused bias add)."""

    def impl(xv, w, b):
        out = jnp.matmul(xv, w)
        if b is not None:
            out = out + b
        return out

    return run_op("linear", impl, (x, weight, bias), {})


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        from ...ops import api as _api
        return _api.assign(x)
    key = next_rng_key()

    def impl(xv, k):
        if axis is None:
            shape = xv.shape
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = tuple(xv.shape[i] if i in axes else 1
                          for i in range(xv.ndim))
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, xv / (1.0 - p), 0.0).astype(xv.dtype)
        return jnp.where(keep, xv, 0.0).astype(xv.dtype)

    return run_op("dropout", impl, (x, key), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        from ...ops import api as _api
        return _api.assign(x)
    key = next_rng_key()

    def impl(xv, k):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(k, 1.0 - p, xv.shape)
        a = jnp.power((1.0 - p) * (1.0 + p * alpha_p ** 2), -0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, xv, alpha_p) + b).astype(xv.dtype)

    return run_op("alpha_dropout", impl, (x, key), {})


def embedding(x, weight, padding_idx=None, sparse=False):
    """Lookup rows of ``weight`` (reference: nn/functional/input.py
    embedding → c_embedding for TP; the TP variant lives in
    parallel/mp_layers.VocabParallelEmbedding)."""

    def impl(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return run_op("embedding", impl, (x, weight), {})


def one_hot(x, num_classes):
    return run_op("one_hot_f", lambda ids: jax.nn.one_hot(ids, num_classes),
                  (x,), {}, differentiable=False)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def impl(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return run_op("cosine_similarity", impl, (x1, x2), {})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    def impl(a, b):
        d = jnp.abs(a - b) + epsilon
        return jnp.power(jnp.sum(jnp.power(d, p), -1, keepdims=keepdim),
                         1.0 / p)

    return run_op("pairwise_distance", impl, (x, y), {})


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor

    def impl(xv):
        if data_format == "NCHW":
            n, c, h, w = xv.shape
            oc = c // (r * r)
            out = jnp.reshape(xv, (n, oc, r, r, h, w))
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return jnp.reshape(out, (n, oc, h * r, w * r))
        n, h, w, c = xv.shape
        oc = c // (r * r)
        out = jnp.reshape(xv, (n, h, w, r, r, oc))
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return jnp.reshape(out, (n, h * r, w * r, oc))

    return run_op("pixel_shuffle", impl, (x,), {})


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor

    def impl(xv):
        n, c, h, w = xv.shape
        out = jnp.reshape(xv, (n, c, h // r, r, w // r, r))
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        return jnp.reshape(out, (n, c * r * r, h // r, w // r))

    return run_op("pixel_unshuffle", impl, (x,), {})


def channel_shuffle(x, groups, data_format="NCHW"):
    def impl(xv):
        n, c, h, w = xv.shape
        out = jnp.reshape(xv, (n, groups, c // groups, h, w))
        out = jnp.swapaxes(out, 1, 2)
        return jnp.reshape(out, (n, c, h, w))

    return run_op("channel_shuffle", impl, (x,), {})


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    def impl(xv):
        channel_last = not data_format.startswith("NC")
        spatial = xv.shape[1:-1] if channel_last else xv.shape[2:]
        if size is not None:
            out_sp = tuple(int(s) for s in (
                size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            out_sp = tuple(int(np.floor(s * f)) for s, f in zip(spatial, sf))
        if channel_last:
            new_shape = (xv.shape[0],) + out_sp + (xv.shape[-1],)
        else:
            new_shape = xv.shape[:2] + out_sp
        method = {"nearest": "nearest", "bilinear": "linear",
                  "trilinear": "linear", "linear": "linear",
                  "bicubic": "cubic", "area": "linear"}[mode]
        return jax.image.resize(xv, new_shape, method=method).astype(xv.dtype)

    return run_op("interpolate", impl, (x,), {})


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference: phi unfold kernel)."""
    def _t(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    if isinstance(paddings, int):
        ph0 = ph1 = pw0 = pw1 = paddings
    elif len(paddings) == 2:
        (ph0, pw0) = paddings
        ph1, pw1 = ph0, pw0
    else:
        ph0, pw0, ph1, pw1 = paddings

    def impl(xv):
        n, c, h, w = xv.shape
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
        out_h = (h + ph0 + ph1 - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (w + pw0 + pw1 - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            xp, (kh, kw), (sh, sw), padding=[(0, 0), (0, 0)],
            rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return jnp.reshape(patches, (n, c * kh * kw, out_h * out_w))

    return run_op("unfold", impl, (x,), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    def _t(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _t(output_sizes)
    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    p = paddings if isinstance(paddings, int) else None
    ph0 = ph1 = pw0 = pw1 = p if p is not None else 0
    if p is None:
        pd = _t(paddings)
        ph0 = ph1 = pd[0]
        pw0 = pw1 = pd[1]

    def impl(xv):
        n = xv.shape[0]
        c = xv.shape[1] // (kh * kw)
        out_h = (oh + ph0 + ph1 - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ow + pw0 + pw1 - (dw * (kw - 1) + 1)) // sw + 1
        cols = jnp.reshape(xv, (n, c, kh, kw, out_h, out_w))
        out = jnp.zeros((n, c, oh + ph0 + ph1, ow + pw0 + pw1), xv.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + out_h * sh:sh,
                             wj:wj + out_w * sw:sw].add(cols[:, :, i, j])
        return out[:, :, ph0:ph0 + oh, pw0:pw0 + ow]

    return run_op("fold", impl, (x,), {})


def bilinear(x1, x2, weight, bias=None):
    def impl(a, b, w, bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi is not None:
            out = out + bi
        return out

    return run_op("bilinear", impl, (x1, x2, weight, bias), {})


# ---------------------------------------------------------------------------
# round-3 API tail (VERDICT r2 item 5)
# ---------------------------------------------------------------------------

def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W dims; padding = [left, right, top, bottom] (reference:
    nn/functional/common.py zeropad2d → pad3d kernel)."""
    l, r, t, b = (int(v) for v in padding)

    def impl(xv):
        if data_format == "NCHW":
            cfg = ((0, 0), (0, 0), (t, b), (l, r))
        else:
            cfg = ((0, 0), (t, b), (l, r), (0, 0))
        return jnp.pad(xv, cfg)

    return run_op("zeropad2d", impl, (x,), {})


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout that drops whole channels (reference:
    nn/functional/common.py feature_alpha_dropout; SELU-preserving noise)."""
    if not training or p == 0.0:
        from ...ops import api as _api
        return _api.assign(x)
    from ...core.rng import next_rng_key
    key = next_rng_key()

    def impl(xv, k):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        shape = (xv.shape[0], xv.shape[1]) + (1,) * (xv.ndim - 2)
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        a = jnp.power((1.0 - p) * (1.0 + p * alpha_p ** 2), -0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, xv, alpha_p) + b).astype(xv.dtype)

    return run_op("feature_alpha_dropout", impl, (x, key), {})


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: nn/functional/extension.py:149 →
    phi gather_tree kernel).  ids/parents: [max_time, batch, beam]; walk
    parent pointers from the last step backwards via ``lax.scan``."""

    def impl(idv, par):
        t = idv.shape[0]
        batch = idv.shape[1]
        beam = idv.shape[2]
        bidx = jnp.arange(batch)[:, None]
        bidx = jnp.broadcast_to(bidx, (batch, beam))

        def step(carry, xs):
            beam_ptr = carry                        # [batch, beam]
            ids_t, par_t = xs                       # each [batch, beam]
            out = ids_t[bidx, beam_ptr]
            nxt = par_t[bidx, beam_ptr]
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(beam)[None, :], (batch, beam))
        # scan from the last time step backwards
        _, outs = jax.lax.scan(step, init, (idv[::-1], par[::-1]))
        return outs[::-1]

    return run_op("gather_tree", impl, (ids, parents), {})


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (reference:
    nn/functional/common.py:2360).  Keeps every positive class center,
    fills to ``num_samples`` with uniformly sampled negatives, remaps
    labels to the compacted id space.  Host-side (data-dependent output
    size) — eager only, like the reference's CPU path."""
    import numpy as np
    from ...core.tensor import Tensor
    from ...core.rng import next_rng_key
    import jax.random as jrandom

    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    lab = lab.reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos,
                                assume_unique=True)
        k = next_rng_key()
        perm = np.asarray(jrandom.permutation(k, len(neg_pool)))
        fill = neg_pool[perm[: num_samples - len(pos)]]
        sampled = np.sort(np.concatenate([pos, fill]))
    remap = np.full((num_classes,), -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    remapped = remap[lab]
    return (Tensor(jnp.asarray(remapped), stop_gradient=True),
            Tensor(jnp.asarray(sampled.astype(np.int64)),
                   stop_gradient=True))
