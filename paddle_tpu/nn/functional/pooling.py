"""Pooling functional ops (reference: python/paddle/nn/functional/pooling.py
→ phi pool kernels).  Implemented with ``lax.reduce_window`` — XLA's native
windowed reduction, which tiles onto the VPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _tup(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _window(x_ndim, ksize, stride, n, channel_last):
    if channel_last:
        dims = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + ksize
        strides = (1, 1) + stride
    return dims, strides


def _pads(padding, n, channel_last, x_ndim):
    if isinstance(padding, str):
        raise ValueError("use explicit int padding for pooling")
    p = _tup(padding, n)
    spatial = [(v, v) for v in p]
    if channel_last:
        return [(0, 0)] + spatial + [(0, 0)]
    return [(0, 0), (0, 0)] + spatial


def _apply_ceil(pads, x_shape, ksize, stride, n, channel_last):
    """ceil_mode: grow the hi padding so reduce_window's floor-division
    output size equals the reference's pure ceil division
    (phi/kernels/funcs/pooling.h:501 PoolOutputSize)."""
    axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
    for (k, s, ax) in zip(ksize, stride, axes):
        lo, hi = pads[ax]
        size = x_shape[ax]
        out = -(-(size + lo + hi - k) // s) + 1     # ceil
        extra = max(0, (out - 1) * s + k - size - lo - hi)
        pads[ax] = (lo, hi + extra)
    return pads


def _maxpool(x, ksize, stride, padding, n, channel_last, return_mask=False,
             ceil_mode=False):
    dims, strides = _window(x.ndim, ksize, stride, n, channel_last)
    pads = _pads(padding, n, channel_last, x.ndim)
    if ceil_mode:
        pads = _apply_ceil(pads, x.shape, ksize, stride, n, channel_last)
    # -inf identity keeps reduce_window on JAX's differentiable max-pool path
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    out = jax.lax.reduce_window(x, neg, jax.lax.max, dims, strides, pads)
    if not return_mask:
        return out
    # indices via reduce_window over (value, flat-index) argmax
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(
        range(2, 2 + n))
    sizes = [x.shape[a] for a in spatial_axes]
    flat = jnp.arange(int(np.prod(sizes))).reshape(sizes)
    shape = [1] * x.ndim
    for a, s in zip(spatial_axes, sizes):
        shape[a] = s
    idx = jnp.broadcast_to(jnp.reshape(flat, shape), x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    # stop_gradient severs the variadic reduce_window from the autodiff
    # graph (its transpose chokes on the symbolic-zero index cotangent);
    # grads flow through the plain max reduce_window above
    _, indices = jax.lax.reduce_window(
        (jax.lax.stop_gradient(x), idx),
        (jnp.asarray(neg, x.dtype), jnp.asarray(-1, idx.dtype)),
        reducer, dims, strides, pads)
    return out, indices


def _avgpool(x, ksize, stride, padding, n, channel_last, exclusive=True,
             ceil_mode=False):
    dims, strides = _window(x.ndim, ksize, stride, n, channel_last)
    pads = _pads(padding, n, channel_last, x.ndim)
    if ceil_mode:
        pads = _apply_ceil(pads, x.shape, ksize, stride, n, channel_last)
    summed = jax.lax.reduce_window(x, jnp.asarray(0, x.dtype), jax.lax.add,
                                   dims, strides, pads)
    if exclusive and any(p[0] or p[1] for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, jnp.asarray(0, x.dtype),
                                       jax.lax.add, dims, strides, pads)
        return summed / counts
    return summed / np.prod(ksize)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL"):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return run_op("max_pool1d", lambda x: _maxpool(
        x, ks, st, padding, 1, data_format == "NLC", return_mask,
        ceil_mode), (x,), {})


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW"):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return run_op("max_pool2d", lambda x: _maxpool(
        x, ks, st, padding, 2, data_format == "NHWC", return_mask,
        ceil_mode), (x,), {})


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return run_op("max_pool3d", lambda x: _maxpool(
        x, ks, st, padding, 3, data_format == "NDHWC", return_mask,
        ceil_mode), (x,), {})


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return run_op("avg_pool1d", lambda x: _avgpool(
        x, ks, st, padding, 1, data_format == "NLC", exclusive,
        ceil_mode), (x,), {})


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return run_op("avg_pool2d", lambda x: _avgpool(
        x, ks, st, padding, 2, data_format == "NHWC", exclusive,
        ceil_mode), (x,), {})


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return run_op("avg_pool3d", lambda x: _avgpool(
        x, ks, st, padding, 3, data_format == "NDHWC", exclusive,
        ceil_mode), (x,), {})


def _adaptive_windows(in_size, out_size):
    # start/end per output index, matching paddle's adaptive pooling
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, channel_last, op="avg"):
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(
        range(2, 2 + n))
    out_sizes = _tup(output_size, n)
    # uniform case → plain pooling
    reduce_fn = jnp.mean if op == "avg" else jnp.max
    for ax, osz in zip(spatial_axes, out_sizes):
        isz = x.shape[ax]
        if isz % osz == 0:
            k = isz // osz
            shape = list(x.shape)
            shape[ax:ax + 1] = [osz, k]
            x = reduce_fn(jnp.reshape(x, shape), axis=ax + 1)
        else:
            starts, ends = _adaptive_windows(isz, osz)
            segs = [reduce_fn(jax.lax.slice_in_dim(x, s, e, axis=ax), axis=ax,
                              keepdims=True) for s, e in zip(starts, ends)]
            x = jnp.concatenate(segs, axis=ax)
    return x


def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return run_op("adaptive_avg_pool1d", lambda x: _adaptive_pool(
        x, output_size, 1, data_format == "NLC", "avg"), (x,), {})


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return run_op("adaptive_avg_pool2d", lambda x: _adaptive_pool(
        x, output_size, 2, data_format == "NHWC", "avg"), (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return run_op("adaptive_avg_pool3d", lambda x: _adaptive_pool(
        x, output_size, 3, data_format == "NDHWC", "avg"), (x,), {})


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    return run_op("adaptive_max_pool1d", lambda x: _adaptive_pool(
        x, output_size, 1, data_format == "NLC", "max"), (x,), {})


def _adaptive_maxpool2d_with_index(x, output_size):
    """NCHW adaptive max pooling returning (out, flat H*W indices) —
    reference max_pool2d_with_index(adaptive=True) semantics.  Non-uniform
    windows are padded to the max window size with -inf and argmaxed."""
    n, c, h, w = x.shape
    oh, ow = _tup(output_size, 2)
    rs, re = _adaptive_windows(h, oh)
    cs, ce = _adaptive_windows(w, ow)
    kh = max(e - s for s, e in zip(rs, re))
    kw = max(e - s for s, e in zip(cs, ce))
    iy = np.minimum(np.array(rs)[:, None] + np.arange(kh)[None], h - 1)
    ix = np.minimum(np.array(cs)[:, None] + np.arange(kw)[None], w - 1)
    vy = (np.arange(kh)[None] < (np.array(re) - np.array(rs))[:, None])
    vx = (np.arange(kw)[None] < (np.array(ce) - np.array(cs))[:, None])
    patches = x[:, :, iy[:, None, :, None], ix[None, :, None, :]]
    # -> [N, C, Oh, Ow, kh, kw]
    valid = (vy[:, None, :, None] & vx[None, :, None, :])[None, None]
    masked = jnp.where(valid, patches, -jnp.inf)
    flat = masked.reshape(n, c, oh, ow, kh * kw)
    amax = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1)
    # recover input coordinates of the argmax
    ky = amax // kw                                   # [N, C, Oh, Ow]
    kx = amax % kw
    iy_t = jnp.asarray(iy)                            # [Oh, kh]
    ix_t = jnp.asarray(ix)                            # [Ow, kw]
    row = iy_t[jnp.arange(oh)[None, None, :, None], ky]
    col = ix_t[jnp.arange(ow)[None, None, None, :], kx]
    return out, (row * w + col).astype(jnp.int32)


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    def impl(x):
        if return_mask:
            if data_format == "NHWC":
                o, i = _adaptive_maxpool2d_with_index(
                    jnp.moveaxis(x, -1, 1), output_size)
                return jnp.moveaxis(o, 1, -1), jnp.moveaxis(i, 1, -1)
            return _adaptive_maxpool2d_with_index(x, output_size)
        return _adaptive_pool(x, output_size, 2, data_format == "NHWC",
                              "max")
    return run_op("adaptive_max_pool2d", impl, (x,), {})


def adaptive_max_pool3d(x, output_size, return_mask=False, data_format="NCDHW"):
    return run_op("adaptive_max_pool3d", lambda x: _adaptive_pool(
        x, output_size, 3, data_format == "NDHWC", "max"), (x,), {})


# ---------------------------------------------------------------------------
# round-3 API tail (VERDICT r2 item 5)
# ---------------------------------------------------------------------------

def _lp_pool(x, norm_type, ksize, stride, padding, n, channel_last,
             ceil_mode):
    """Power-average pooling: (sum |x|^p)^(1/p) over the window (reference:
    nn/functional/pooling.py:2403 lp_pool1d / :2534 lp_pool2d)."""
    def impl(xv):
        p = float(norm_type)
        dims, strides = _window(xv.ndim, ksize, stride, n, channel_last)
        pads = _pads(padding, n, channel_last, xv.ndim)
        if ceil_mode:
            pads = _apply_ceil(pads, xv.shape, ksize, stride, n, channel_last)
        if p == float("inf"):
            neg = -jnp.inf
            return jax.lax.reduce_window(jnp.abs(xv), neg, jax.lax.max,
                                         dims, strides, pads)
        # reference kernel uses x^p with NO abs (funcs/pooling.h LPPool
        # 'powf(x, norm_type)'); negative inputs propagate sign/NaN as there
        powed = jnp.power(xv, p)
        summed = jax.lax.reduce_window(powed, jnp.asarray(0, xv.dtype),
                                       jax.lax.add, dims, strides, pads)
        return jnp.power(summed, 1.0 / p)

    return run_op("lp_pool", impl, (x,), {})


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    ks = _tup(kernel_size, 1)
    st = ks if stride is None else _tup(stride, 1)
    return _lp_pool(x, norm_type, ks, st, padding, 1,
                    data_format == "NLC", ceil_mode)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    ks = _tup(kernel_size, 2)
    st = ks if stride is None else _tup(stride, 2)
    return _lp_pool(x, norm_type, ks, st, padding, 2,
                    data_format == "NHWC", ceil_mode)


def _max_unpool(x, indices, ksize, stride, padding, n, output_size,
                data_format):
    """Scatter pooled values back to the argmax positions (reference:
    nn/functional/pooling.py:750/873/1005 → phi unpool kernels).  `indices`
    are the flat spatial indices produced by max_poolNd(return_mask=True)."""
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    if channel_last:
        raise ValueError("max_unpool supports channel-first layouts only "
                         "(matches reference NCL/NCHW/NCDHW)")

    def impl(xv, idx):
        in_spatial = xv.shape[2:]
        if output_size is not None:
            out_spatial = tuple(int(s) for s in output_size)[-n:]
        else:
            out_spatial = tuple(
                (i - 1) * s - 2 * p + k for i, s, p, k in zip(
                    in_spatial, stride, _tup(padding, n), ksize))
        nb, c = xv.shape[:2]
        flat_out = int(np.prod(out_spatial))
        xflat = xv.reshape(nb, c, -1)
        iflat = idx.reshape(nb, c, -1).astype(jnp.int32)
        out = jnp.zeros((nb, c, flat_out), xv.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].set(v)))(out, iflat, xflat)
        return out.reshape((nb, c) + out_spatial)

    return run_op("max_unpool", impl, (x, indices), {})


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    ks = _tup(kernel_size, 1)
    st = ks if stride is None else _tup(stride, 1)
    return _max_unpool(x, indices, ks, st, padding, 1, output_size,
                       data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    ks = _tup(kernel_size, 2)
    st = ks if stride is None else _tup(stride, 2)
    return _max_unpool(x, indices, ks, st, padding, 2, output_size,
                       data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    ks = _tup(kernel_size, 3)
    st = ks if stride is None else _tup(stride, 3)
    return _max_unpool(x, indices, ks, st, padding, 3, output_size,
                       data_format)


def _fractional_regions(in_size, out_size, kernel, u):
    """Fractional pooling split points (reference:
    nn/functional/pooling.py:2087 formula; phi funcs/pooling.h:139):
    start = ceil(alpha*(i+u) - 1), end = ceil(alpha*(i+1+u) - 1)."""
    alpha = in_size / out_size
    starts, ends = [], []
    for i in range(out_size):
        s = int(np.ceil(alpha * (i + u) - 1.0))
        e = int(np.ceil(alpha * (i + 1 + u) - 1.0))
        s = max(0, min(s, in_size - 1))
        if kernel:
            e = min(s + kernel, in_size)
        e = max(s + 1, min(e, in_size))
        starts.append(s)
        ends.append(e)
    return starts, ends


def _fractional_max_pool(x, output_size, kernel_size, random_u, return_mask,
                         n):
    if random_u is None:
        from ...core.rng import next_rng_key
        import jax.random as jrandom
        u = float(jrandom.uniform(next_rng_key(), ()))
    else:
        u = float(random_u)
        if not (0 < u < 1):
            raise ValueError("random_u must be in (0, 1)")
    out_sz = _tup(output_size, n)
    ker = _tup(kernel_size, n) if kernel_size is not None else (None,) * n

    def impl(xv):
        spatial = xv.shape[2:]
        regions = [
            _fractional_regions(spatial[d], out_sz[d], ker[d], u)
            for d in range(n)]
        # gather max per (cartesian) region; python loops run at trace
        # time over static out sizes — XLA sees only slices + maxes
        sizes = spatial
        flat_idx = jnp.arange(int(np.prod(sizes))).reshape(sizes)
        outs = np.empty(tuple(out_sz), object)
        idxs = np.empty(tuple(out_sz), object)
        for pos in np.ndindex(*out_sz):
            sl = tuple(slice(regions[d][0][pos[d]], regions[d][1][pos[d]])
                       for d in range(n))
            region = xv[(slice(None), slice(None)) + sl]
            red = tuple(range(2, 2 + n))
            m = jnp.max(region, axis=red)
            outs[pos] = m
            if return_mask:
                rflat = region.reshape(region.shape[:2] + (-1,))
                am = jnp.argmax(rflat, axis=-1)
                ridx = flat_idx[sl].reshape(-1)
                idxs[pos] = jnp.take(ridx, am)
        out = jnp.stack([outs[p] for p in np.ndindex(*out_sz)], -1)
        out = out.reshape(out.shape[:2] + tuple(out_sz))
        if not return_mask:
            return out
        idx = jnp.stack([idxs[p] for p in np.ndindex(*out_sz)], -1)
        idx = idx.reshape(idx.shape[:2] + tuple(out_sz))
        return out, idx

    return run_op("fractional_max_pool", impl, (x,), {})


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling 2D (reference: nn/functional/pooling.py:2087,
    Graham 2015)."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling 3D (reference: nn/functional/pooling.py:2242)."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3)
