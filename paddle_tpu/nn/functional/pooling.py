"""Pooling functional ops (reference: python/paddle/nn/functional/pooling.py
→ phi pool kernels).  Implemented with ``lax.reduce_window`` — XLA's native
windowed reduction, which tiles onto the VPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _tup(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _window(x_ndim, ksize, stride, n, channel_last):
    if channel_last:
        dims = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + ksize
        strides = (1, 1) + stride
    return dims, strides


def _pads(padding, n, channel_last, x_ndim):
    if isinstance(padding, str):
        raise ValueError("use explicit int padding for pooling")
    p = _tup(padding, n)
    spatial = [(v, v) for v in p]
    if channel_last:
        return [(0, 0)] + spatial + [(0, 0)]
    return [(0, 0), (0, 0)] + spatial


def _apply_ceil(pads, x_shape, ksize, stride, n, channel_last):
    """ceil_mode: grow the hi padding so reduce_window's floor-division
    output size equals the reference's pure ceil division
    (phi/kernels/funcs/pooling.h:501 PoolOutputSize)."""
    axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
    for (k, s, ax) in zip(ksize, stride, axes):
        lo, hi = pads[ax]
        size = x_shape[ax]
        out = -(-(size + lo + hi - k) // s) + 1     # ceil
        extra = max(0, (out - 1) * s + k - size - lo - hi)
        pads[ax] = (lo, hi + extra)
    return pads


def _maxpool(x, ksize, stride, padding, n, channel_last, return_mask=False,
             ceil_mode=False):
    dims, strides = _window(x.ndim, ksize, stride, n, channel_last)
    pads = _pads(padding, n, channel_last, x.ndim)
    if ceil_mode:
        pads = _apply_ceil(pads, x.shape, ksize, stride, n, channel_last)
    # -inf identity keeps reduce_window on JAX's differentiable max-pool path
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    out = jax.lax.reduce_window(x, neg, jax.lax.max, dims, strides, pads)
    if not return_mask:
        return out
    # indices via reduce_window over (value, flat-index) argmax
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(
        range(2, 2 + n))
    sizes = [x.shape[a] for a in spatial_axes]
    flat = jnp.arange(int(np.prod(sizes))).reshape(sizes)
    shape = [1] * x.ndim
    for a, s in zip(spatial_axes, sizes):
        shape[a] = s
    idx = jnp.broadcast_to(jnp.reshape(flat, shape), x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    # stop_gradient severs the variadic reduce_window from the autodiff
    # graph (its transpose chokes on the symbolic-zero index cotangent);
    # grads flow through the plain max reduce_window above
    _, indices = jax.lax.reduce_window(
        (jax.lax.stop_gradient(x), idx),
        (jnp.asarray(neg, x.dtype), jnp.asarray(-1, idx.dtype)),
        reducer, dims, strides, pads)
    return out, indices


def _avgpool(x, ksize, stride, padding, n, channel_last, exclusive=True,
             ceil_mode=False):
    dims, strides = _window(x.ndim, ksize, stride, n, channel_last)
    pads = _pads(padding, n, channel_last, x.ndim)
    if ceil_mode:
        pads = _apply_ceil(pads, x.shape, ksize, stride, n, channel_last)
    summed = jax.lax.reduce_window(x, jnp.asarray(0, x.dtype), jax.lax.add,
                                   dims, strides, pads)
    if exclusive and any(p[0] or p[1] for p in pads):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, jnp.asarray(0, x.dtype),
                                       jax.lax.add, dims, strides, pads)
        return summed / counts
    return summed / np.prod(ksize)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL"):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return run_op("max_pool1d", lambda x: _maxpool(
        x, ks, st, padding, 1, data_format == "NLC", return_mask,
        ceil_mode), (x,), {})


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW"):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return run_op("max_pool2d", lambda x: _maxpool(
        x, ks, st, padding, 2, data_format == "NHWC", return_mask,
        ceil_mode), (x,), {})


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return run_op("max_pool3d", lambda x: _maxpool(
        x, ks, st, padding, 3, data_format == "NDHWC", return_mask,
        ceil_mode), (x,), {})


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return run_op("avg_pool1d", lambda x: _avgpool(
        x, ks, st, padding, 1, data_format == "NLC", exclusive,
        ceil_mode), (x,), {})


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return run_op("avg_pool2d", lambda x: _avgpool(
        x, ks, st, padding, 2, data_format == "NHWC", exclusive,
        ceil_mode), (x,), {})


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return run_op("avg_pool3d", lambda x: _avgpool(
        x, ks, st, padding, 3, data_format == "NDHWC", exclusive,
        ceil_mode), (x,), {})


def _adaptive_windows(in_size, out_size):
    # start/end per output index, matching paddle's adaptive pooling
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, channel_last, op="avg"):
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(
        range(2, 2 + n))
    out_sizes = _tup(output_size, n)
    # uniform case → plain pooling
    reduce_fn = jnp.mean if op == "avg" else jnp.max
    for ax, osz in zip(spatial_axes, out_sizes):
        isz = x.shape[ax]
        if isz % osz == 0:
            k = isz // osz
            shape = list(x.shape)
            shape[ax:ax + 1] = [osz, k]
            x = reduce_fn(jnp.reshape(x, shape), axis=ax + 1)
        else:
            starts, ends = _adaptive_windows(isz, osz)
            segs = [reduce_fn(jax.lax.slice_in_dim(x, s, e, axis=ax), axis=ax,
                              keepdims=True) for s, e in zip(starts, ends)]
            x = jnp.concatenate(segs, axis=ax)
    return x


def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return run_op("adaptive_avg_pool1d", lambda x: _adaptive_pool(
        x, output_size, 1, data_format == "NLC", "avg"), (x,), {})


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return run_op("adaptive_avg_pool2d", lambda x: _adaptive_pool(
        x, output_size, 2, data_format == "NHWC", "avg"), (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return run_op("adaptive_avg_pool3d", lambda x: _adaptive_pool(
        x, output_size, 3, data_format == "NDHWC", "avg"), (x,), {})


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    return run_op("adaptive_max_pool1d", lambda x: _adaptive_pool(
        x, output_size, 1, data_format == "NLC", "max"), (x,), {})


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return run_op("adaptive_max_pool2d", lambda x: _adaptive_pool(
        x, output_size, 2, data_format == "NHWC", "max"), (x,), {})


def adaptive_max_pool3d(x, output_size, return_mask=False, data_format="NCDHW"):
    return run_op("adaptive_max_pool3d", lambda x: _adaptive_pool(
        x, output_size, 3, data_format == "NDHWC", "max"), (x,), {})
