"""``paddle_tpu.nn.functional`` — functional API surface (reference:
python/paddle/nn/functional/__init__.py)."""

# activations come from the generated op namespace
from ...ops.api import (  # noqa: F401
    relu, relu6, sigmoid, log_sigmoid, tanh, tanhshrink, gelu, silu, swish,
    mish, hardswish, hardsigmoid, hardtanh, hardshrink, softshrink,
    leaky_relu, elu, selu, celu, prelu, rrelu, softmax, log_softmax, softmin,
    softplus, softsign, thresholded_relu, maxout, glu, swiglu, gumbel_softmax,
    sigmoid as sigmoid_,  # compat alias
)
from ...ops.api import pad, one_hot  # noqa: F401

from .common import (  # noqa: F401
    alpha_dropout, bilinear, channel_shuffle, cosine_similarity, dropout,
    dropout2d, dropout3d, embedding, fold, interpolate, linear,
    pairwise_distance, pixel_shuffle, pixel_unshuffle, unfold, upsample,
)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize, rms_norm, fused_layer_norm,
    fused_bias_dropout_residual_layer_norm,
)
from .loss import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits,
    cosine_embedding_loss, cross_entropy, ctc_loss, hinge_embedding_loss,
    huber_loss, kl_div, l1_loss, label_smooth, log_loss, margin_ranking_loss,
    mse_loss, nll_loss, sigmoid_focal_loss, smooth_l1_loss,
    softmax_with_cross_entropy, square_error_cost, triplet_margin_loss,
    soft_margin_loss, multi_margin_loss, multi_label_soft_margin_loss,
    gaussian_nll_loss, poisson_nll_loss, triplet_margin_with_distance_loss,
    rnnt_loss, fused_linear_cross_entropy,
)
from .attention import (  # noqa: F401
    flash_attention, flash_attn_unpadded, scaled_dot_product_attention,
    sequence_mask,
)

# ---- round-3 API tail (VERDICT r2 item 5) ----
from .loss import (  # noqa: F401
    adaptive_log_softmax_with_loss, dice_loss, hsigmoid_loss,
    margin_cross_entropy, npair_loss,
)
from .attention import (  # noqa: F401
    flash_attn_qkvpacked, flash_attn_varlen_qkvpacked, sparse_attention,
    flash_attention_with_sparse_mask,
)
from .pooling import (  # noqa: F401
    fractional_max_pool2d, fractional_max_pool3d, lp_pool1d, lp_pool2d,
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .vision import (  # noqa: F401
    affine_grid, grid_sample, temporal_shift,
)
from .common import (  # noqa: F401
    class_center_sample, feature_alpha_dropout, gather_tree, zeropad2d,
)
from ._inplace import (  # noqa: F401
    elu_, hardtanh_, leaky_relu_, relu_, softmax_, tanh_, thresholded_relu_,
)
