"""Normalization functional ops (reference:
python/paddle/nn/functional/norm.py; rms_norm from
phi/kernels/gpu/rms_norm_kernel.cu).  The jnp forms here are the numeric
references; the Pallas fused variants live in ops/pallas and are dispatched
by the incubate fused APIs."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _norm_axes(x_ndim, channel_axis):
    return tuple(i for i in range(x_ndim) if i != channel_axis and i != 0) \
        if False else None


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    """Returns normalized output; in training mode also updates the running
    stats *in place* on the passed Tensors (eager path) — mirroring the
    reference's mutable mean/variance outputs (phi batch_norm kernel)."""
    from ...core.tensor import Tensor

    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1 \
        if hasattr(x, "ndim") else 1

    def impl(xv, rm, rv, w, b):
        axes = tuple(i for i in range(xv.ndim) if i != ch_axis)
        if training and not use_global_stats:
            mean = jnp.mean(xv, axis=axes)
            var = jnp.var(xv, axis=axes)
        else:
            mean, var = rm, rv
        shape = [1] * xv.ndim
        shape[ch_axis] = -1
        inv = jax.lax.rsqrt(var + epsilon)
        out = (xv - jnp.reshape(mean, shape)) * jnp.reshape(inv, shape)
        if w is not None:
            out = out * jnp.reshape(w, shape)
        if b is not None:
            out = out + jnp.reshape(b, shape)
        if training and not use_global_stats:
            n = int(np.prod([xv.shape[a] for a in axes]))
            unbiased = var * n / max(n - 1, 1)
            new_rm = momentum * rm + (1 - momentum) * mean
            new_rv = momentum * rv + (1 - momentum) * unbiased
            return out, new_rm, new_rv
        return out, rm, rv

    res = run_op("batch_norm", impl, (x, running_mean, running_var, weight,
                                      bias), {})
    out, new_rm, new_rv = res
    if training and not use_global_stats:
        if isinstance(running_mean, Tensor):
            running_mean._value = new_rm._value if isinstance(new_rm, Tensor) \
                else new_rm
        if isinstance(running_var, Tensor):
            running_var._value = new_rv._value if isinstance(new_rv, Tensor) \
                else new_rv
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))

    def impl(xv, w, b):
        axes = tuple(range(xv.ndim - n, xv.ndim))
        mean = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.var(xv, axis=axes, keepdims=True)
        out = (xv - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return run_op("layer_norm", impl, (x, weight, bias), {})


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1):
    """Pure-jnp RMSNorm reference (fused Pallas variant:
    paddle_tpu.ops.pallas.rms_norm; reference CUDA:
    phi/kernels/gpu/rms_norm_kernel.cu)."""

    def impl(xv, w, b):
        axis = begin_norm_axis if begin_norm_axis >= 0 else xv.ndim + begin_norm_axis
        axes = tuple(range(axis, xv.ndim))
        ms = jnp.mean(jnp.square(xv.astype(jnp.float32)), axis=axes,
                      keepdims=True)
        out = (xv.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(
            xv.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return run_op("rms_norm", impl, (x, weight, bias), {})


def fused_layer_norm(x, weight, bias, epsilon=1e-5):
    """Single-pass Pallas layer_norm (ops/pallas/norms.py): mean/var/
    normalize/affine in one VMEM sweep with an analytic VJP.  Call sites
    gate on the Pallas dispatch rule (models.gpt._pallas_epilogue_gate);
    the jnp reference is :func:`layer_norm`."""
    def impl(xv, w, b):
        from ...ops import pallas as _pk
        return _pk.layer_norm(xv, w, b, epsilon)

    return run_op("fused_layer_norm_f", impl, (x, weight, bias), {})


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias, ln_weight, ln_bias, dropout_rate=0.0,
        epsilon=1e-5, training=False, return_add_out=False):
    """Pallas epilogue ``ln(residual + dropout(x + bias))`` in one kernel
    (ops/pallas/norms.py): the transformer residual-add and the next
    layer norm never round-trip HBM separately.  With
    ``return_add_out=True`` also returns the pre-norm residual stream
    (what the unfused path calls ``residual + drop(proj(...))``)."""
    def impl(xv, res, b, w, lb):
        from ...ops import pallas as _pk
        out, add = _pk.fused_bias_dropout_residual_layer_norm(
            xv, res, b, w, lb, dropout_rate, epsilon, training)
        return (out, add) if return_add_out else out

    return run_op("fused_bias_dropout_residual_ln_f", impl,
                  (x, residual, bias, ln_weight, ln_bias), {})


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    channel_last = not data_format.startswith("NC")

    def impl(xv, w, b):
        if channel_last:
            xv_ = jnp.moveaxis(xv, -1, 1)
        else:
            xv_ = xv
        N, C = xv_.shape[0], xv_.shape[1]
        g = num_groups
        rest = xv_.shape[2:]
        grouped = jnp.reshape(xv_, (N, g, C // g) + rest)
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped, axis=axes, keepdims=True)
        var = jnp.var(grouped, axis=axes, keepdims=True)
        outg = (grouped - mean) * jax.lax.rsqrt(var + epsilon)
        out = jnp.reshape(outg, xv_.shape)
        shape = (1, C) + (1,) * len(rest)
        if w is not None:
            out = out * jnp.reshape(w, shape)
        if b is not None:
            out = out + jnp.reshape(b, shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return run_op("group_norm", impl, (x, weight, bias), {})


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    def impl(xv, w, b):
        axes = tuple(range(2, xv.ndim))
        mean = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.var(xv, axis=axes, keepdims=True)
        out = (xv - mean) * jax.lax.rsqrt(var + eps)
        if w is not None:
            shape = (1, -1) + (1,) * (xv.ndim - 2)
            out = out * jnp.reshape(w, shape)
        if b is not None:
            shape = (1, -1) + (1,) * (xv.ndim - 2)
            out = out + jnp.reshape(b, shape)
        return out

    return run_op("instance_norm", impl, (x, weight, bias), {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    def impl(xv):
        ch_axis = 1 if data_format.startswith("NC") else xv.ndim - 1
        sq = jnp.square(xv)
        moved = jnp.moveaxis(sq, ch_axis, -1)
        pad = [(0, 0)] * (moved.ndim - 1) + [(size // 2, (size - 1) // 2)]
        padded = jnp.pad(moved, pad)
        win = jax.lax.reduce_window(
            padded, jnp.asarray(0, xv.dtype), jax.lax.add,
            (1,) * (moved.ndim - 1) + (size,), (1,) * moved.ndim,
            [(0, 0)] * moved.ndim)
        win = jnp.moveaxis(win, -1, ch_axis)
        return xv / jnp.power(k + alpha * win, beta)

    return run_op("local_response_norm", impl, (x,), {})


def normalize(x, p=2, axis=1, epsilon=1e-12):
    def impl(xv):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(xv), axis=axis, keepdims=True))
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(xv), p), axis=axis,
                                  keepdims=True), 1.0 / p)
        return xv / jnp.maximum(n, epsilon)

    return run_op("normalize", impl, (x,), {})
