"""Vision functional ops (reference: python/paddle/nn/functional/vision.py
-> phi affine_grid / grid_sample kernels).  Pure-jnp gather formulations —
XLA fuses the index arithmetic; no CUDA texture units needed on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _unnormalize(coord, size, align_corners):
    """Map [-1, 1] grid coords to pixel space (vision.py:140 grid_sample)."""
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(coord, low, high):
    """Reflection padding: fold coordinates into [low, high] by reflecting
    at the boundaries (phi grid_sample_utils reflect semantics)."""
    span = high - low
    if span <= 0:
        return jnp.zeros_like(coord)
    coord = jnp.abs(coord - low) % (2 * span)
    return low + jnp.where(coord > span, 2 * span - coord, coord)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D/3D affine sampling grid (reference: nn/functional/vision.py:38).

    theta [N,2,3] -> grid [N,H,W,2]; theta [N,3,4] -> grid [N,D,H,W,3].
    """
    def impl(th):
        shape = [int(s) for s in np.asarray(out_shape).reshape(-1)]
        nd = 2 if th.shape[-2:] == (2, 3) else 3
        spatial = shape[2:]            # (H, W) or (D, H, W)

        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size, dtype=th.dtype)
            step = 2.0 / size
            return -1.0 + step / 2 + step * jnp.arange(size, dtype=th.dtype)

        if nd == 2:
            h, w = spatial
            ys, xs = jnp.meshgrid(axis_coords(h), axis_coords(w),
                                  indexing="ij")
            base = jnp.stack([xs, ys, jnp.ones_like(xs)], -1)  # [H,W,3]
            grid = jnp.einsum("hwk,nck->nhwc", base, th)       # [N,H,W,2]
        else:
            d, h, w = spatial
            zs, ys, xs = jnp.meshgrid(axis_coords(d), axis_coords(h),
                                      axis_coords(w), indexing="ij")
            base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], -1)
            grid = jnp.einsum("dhwk,nck->ndhwc", base, th)
        return grid

    return run_op("affine_grid", impl, (theta,), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at grid locations (reference: nn/functional/vision.py:140,
    phi/kernels/cpu/grid_sample_kernel.cc).  4-D: x [N,C,H,W], grid
    [N,Ho,Wo,2]; 5-D: x [N,C,D,H,W], grid [N,Do,Ho,Wo,3]."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, "
                         f"got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode}")

    def impl(xv, gv):
        nd = xv.ndim - 2
        sizes = xv.shape[2:]                       # spatial, slow→fast
        # grid stores (x, y[, z]) = fast→slow axes; flip to match sizes
        coords = [gv[..., i] for i in range(nd)][::-1]
        pix = []
        for c, s in zip(coords, sizes):
            p = _unnormalize(c.astype(jnp.float32), s, align_corners)
            if padding_mode == "border":
                p = jnp.clip(p, 0, s - 1)
            elif padding_mode == "reflection":
                p = _reflect(p, 0.0 if align_corners else -0.5,
                             (s - 1.0) if align_corners else (s - 0.5))
                p = jnp.clip(p, 0, s - 1)
            pix.append(p)

        def gather(idx_list):
            """x[n, :, i0, i1, ...] with zero padding outside."""
            valid = jnp.ones(idx_list[0].shape, dtype=bool)
            clipped = []
            for i, s in zip(idx_list, sizes):
                valid &= (i >= 0) & (i <= s - 1)
                clipped.append(jnp.clip(i, 0, s - 1).astype(jnp.int32))
            n = xv.shape[0]
            bidx = jnp.arange(n).reshape((n,) + (1,) * (gv.ndim - 2))
            bidx = jnp.broadcast_to(bidx, clipped[0].shape)
            xs = jnp.moveaxis(xv, 1, -1)           # [N, *spatial, C]
            out = xs[(bidx,) + tuple(clipped)]     # [N, out..., C]
            out = jnp.where(valid[..., None], out, 0.0)
            return out, valid

        if mode == "nearest":
            idx = [jnp.floor(p + 0.5) for p in pix]
            out, _ = gather(idx)
        else:
            lo = [jnp.floor(p) for p in pix]
            frac = [p - l for p, l in zip(pix, lo)]
            out = 0.0
            for corner in range(2 ** nd):
                idx, w = [], 1.0
                for a in range(nd):
                    hi_bit = (corner >> a) & 1
                    idx.append(lo[a] + hi_bit)
                    w = w * (frac[a] if hi_bit else (1.0 - frac[a]))
                g, _ = gather(idx)
                out = out + g * w[..., None]
        out = jnp.moveaxis(out, -1, 1)             # [N, C, out...]
        return out.astype(xv.dtype)

    return run_op("grid_sample", impl, (x, grid), {})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """Temporal Shift Module (reference: nn/functional/extension.py:247,
    phi/kernels/impl/temporal_shift_kernel_impl.h)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"bad data_format {data_format}")

    def impl(xv):
        v = jnp.moveaxis(xv, -1, 1) if data_format == "NHWC" else xv
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(v, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        slice1 = pad[:, :seg_num, :c1]             # shift left  (past)
        slice2 = pad[:, 2:seg_num + 2, c1:c2]      # shift right (future)
        slice3 = pad[:, 1:seg_num + 1, c2:]        # no shift
        out = jnp.concatenate([slice1, slice2, slice3], 2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return run_op("temporal_shift", impl, (x,), {})
