"""In-place functional activation variants (reference:
python/paddle/nn/functional/activation.py relu_ / softmax_ / ...).

TPU tensors are immutable jax.Arrays; "in-place" here means rebinding the
Tensor box's value/autograd node — same API contract as the reference's
inplace ops (the input Tensor observes the new value), zero-copy under jit.
"""

from ...core.tensor import inplace_rebind as _rebind
from ...ops import api as _api


def relu_(x, name=None):
    return _rebind(x, _api.relu(x))


def elu_(x, alpha=1.0, name=None):
    return _rebind(x, _api.elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    return _rebind(x, _api.hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return _rebind(x, _api.leaky_relu(x, negative_slope))


def softmax_(x, axis=-1, dtype=None, name=None):
    out = _api.softmax(x, axis)
    if dtype is not None:
        out = out.astype(dtype)
    return _rebind(x, out)


def tanh_(x, name=None):
    return _rebind(x, _api.tanh(x))


def thresholded_relu_(x, threshold=1.0, name=None):
    return _rebind(x, _api.thresholded_relu(x, threshold))
