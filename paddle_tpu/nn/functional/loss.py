"""Loss functional ops (reference: python/paddle/nn/functional/loss.py →
phi cross_entropy/... kernels).  softmax+CE fuses in XLA; the TP-sharded
variant (ParallelCrossEntropy) lives in parallel/mp_layers."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    def impl(logits, lab, w):
        # large-vocab 3-D hard-label case: chunked softmax-CE
        # (ops/fused_cross_entropy) — never builds the fp32 log-prob
        # copy or its softmax backward residual over the class dim
        from ...ops import fused_cross_entropy as _fce
        n_cls_ = logits.shape[axis]
        if (use_softmax and not soft_label and w is None
                and logits.ndim >= 3 and n_cls_ >= _fce.MIN_FUSED_VOCAB
                and axis in (-1, logits.ndim - 1)
                and not (lab.ndim == logits.ndim
                         and lab.shape == logits.shape)):
            lab_ = lab
            if lab_.ndim == logits.ndim:
                lab_ = jnp.squeeze(lab_, axis)
            loss = _fce.softmax_nll_chunked(
                logits, lab_, ignore_index=ignore_index,
                label_smoothing=label_smoothing)
            valid = lab_ != ignore_index
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
            return _reduce(loss, reduction)
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
            jnp.log(jnp.clip(logits, 1e-30, None))
        n_cls = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            tgt = lab
            loss = -jnp.sum(tgt * lp, axis=axis)
            valid = jnp.ones(loss.shape, bool)
        else:
            lab_ = lab
            if lab_.ndim == logits.ndim:
                lab_ = jnp.squeeze(lab_, axis)
            valid = lab_ != ignore_index
            safe = jnp.where(valid, lab_, 0)
            if label_smoothing > 0.0:
                onehot = jax.nn.one_hot(safe, n_cls, dtype=lp.dtype, axis=axis)
                tgt = onehot * (1 - label_smoothing) + label_smoothing / n_cls
                loss = -jnp.sum(tgt * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.squeeze(loss, axis)
            if w is not None:
                loss = loss * w[safe]
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if w is not None and not soft_label:
                lab_ = lab if lab.ndim < logits.ndim else jnp.squeeze(lab, axis)
                safe = jnp.where(valid, lab_, 0)
                denom = jnp.sum(jnp.where(valid, w[safe], 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(lp.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return run_op("cross_entropy", impl, (input, label, weight), {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """softmax + CE as one op (reference c_softmax_with_cross_entropy).

    With ``return_softmax=True`` the softmax is ``exp`` of the log-probs
    the loss already computed — the class-dim reduction runs ONCE (the
    old form recomputed a second full softmax from the logits)."""
    def impl(lg, lab):
        lp = jax.nn.log_softmax(lg, axis=axis)
        if soft_label or (lab.ndim == lg.ndim and lab.shape == lg.shape):
            loss = -jnp.sum(lab * lp, axis=axis, keepdims=True)
        else:
            lab_ = lab
            squeeze = lab_.ndim == lg.ndim
            if squeeze:
                lab_ = jnp.squeeze(lab_, axis)
            valid = lab_ != ignore_index
            safe = jnp.where(valid, lab_, 0)
            loss = -jnp.take_along_axis(
                lp, jnp.expand_dims(safe, axis), axis=axis)
            loss = jnp.where(jnp.expand_dims(valid, axis), loss, 0.0)
            if not squeeze:
                loss = jnp.squeeze(loss, axis)
        if return_softmax:
            return loss, jnp.exp(lp)     # reuse lp: vocab work done once
        return loss

    return run_op("softmax_with_cross_entropy", impl, (logits, label), {})


def fused_linear_cross_entropy(input, weight, label, *, w_layout="vh",
                               chunk=None, ignore_index=-100,
                               reduction="mean", label_smoothing=0.0,
                               backend=None):
    """Logits-free fused LM-head loss: cross-entropy of
    ``softmax(input @ head)`` computed by streaming vocab chunks
    (ops/fused_cross_entropy.linear_cross_entropy) — the ``[..., V]``
    logits tensor is never materialized, forward or backward.

    ``input``: [..., H] activations; ``weight``: [V, H]
    (``w_layout="vh"``, tied-embedding layout) or [H, V] (``"hv"``,
    Linear layout); ``label``: [...] int.  Reduction semantics match
    :func:`cross_entropy` ("mean" divides by the number of
    non-``ignore_index`` tokens)."""
    def impl(xv, wv, lab):
        from ...ops.fused_cross_entropy import linear_cross_entropy
        nll = linear_cross_entropy(
            xv, wv, lab, w_layout=w_layout, chunk=chunk,
            ignore_index=ignore_index, label_smoothing=label_smoothing,
            backend=backend)
        if reduction == "mean":
            valid = lab != ignore_index
            denom = jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
            return jnp.sum(nll) / denom
        return _reduce(nll, reduction)

    return run_op("fused_linear_cross_entropy", impl,
                  (input, weight, label), {})


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    def impl(lp, lab, w):
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        loss = -jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0] \
            if lp.ndim == 2 else -jnp.take_along_axis(
                lp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        if w is not None:
            loss = loss * w[safe]
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(w[safe] * valid) if w is not None else \
                jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return run_op("nll_loss", impl, (input, label, weight), {})


def mse_loss(input, label, reduction="mean"):
    return run_op("mse_loss", lambda x, y: _reduce(jnp.square(x - y),
                                                   reduction),
                  (input, label), {})


def l1_loss(input, label, reduction="mean"):
    return run_op("l1_loss", lambda x, y: _reduce(jnp.abs(x - y), reduction),
                  (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def impl(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return run_op("smooth_l1_loss", impl, (input, label), {})


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def impl(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return run_op("huber_loss", impl, (input, label), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    def impl(p, y, w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return run_op("binary_cross_entropy", impl, (input, label, weight), {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    def impl(z, y, w, pw):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            base = base * w
        return _reduce(base, reduction)

    return run_op("bce_with_logits", impl, (logit, label, weight, pos_weight),
                  {})


def kl_div(input, label, reduction="mean", log_target=False):
    def impl(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-30, None)) - lp),
                             0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return run_op("kl_div", impl, (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def impl(x1, x2, y):
        cos = jnp.sum(x1 * x2, -1) / (
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return run_op("cosine_embedding_loss", impl, (input1, input2, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def impl(x, o, y):
        return _reduce(jnp.maximum(0.0, -y * (x - o) + margin), reduction)

    return run_op("margin_ranking_loss", impl, (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def impl(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)

    return run_op("hinge_embedding_loss", impl, (input, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def impl(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p),
                                     -1), 1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)

    return run_op("triplet_margin_loss", impl, (input, positive, negative), {})


def label_smooth(label, prior_dist=None, epsilon=0.1):
    def impl(y, pd):
        n = y.shape[-1]
        if pd is not None:
            return (1 - epsilon) * y + epsilon * pd
        return (1 - epsilon) * y + epsilon / n

    return run_op("label_smooth", impl, (label, prior_dist), {})


def square_error_cost(input, label):
    return run_op("square_error_cost", lambda x, y: jnp.square(x - y),
                  (input, label), {})


def log_loss(input, label, epsilon=1e-4):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log1p(epsilon - p)

    return run_op("log_loss", impl, (input, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def impl(z, y, nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if nrm is not None:
            loss = loss / nrm
        return _reduce(loss, reduction)

    return run_op("sigmoid_focal_loss", impl, (logit, label, normalizer), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist temporal classification loss.

    Reference: python/paddle/nn/functional/loss.py ``ctc_loss`` backed by
    warpctc (phi/kernels/impl/warpctc_kernel_impl.h).  TPU-native: the
    standard log-space forward algorithm as one ``lax.scan`` over time —
    static shapes, fully batched, differentiable by autodiff (no
    hand-written warpctc gradient needed).

    log_probs: [T, B, C] (log-softmaxed); labels: [B, L] int; returns per
    paddle semantics (reduction "mean" divides by label_lengths first).
    """
    NEG = -1e30

    def impl(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        lab = lab.astype(jnp.int32)
        in_len = in_len.reshape(B).astype(jnp.int32)
        lab_len = lab_len.reshape(B).astype(jnp.int32)
        # extended label sequence: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        # emission log-probs for the extended sequence: [T, B, S]
        lp_ext = jnp.take_along_axis(
            lp, jnp.broadcast_to(ext[None], (T, B, S)), axis=2)
        # transition mask: s -> s allowed from s-2 when ext[s] != blank and
        # ext[s] != ext[s-2]
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
        allow_skip = (ext != blank) & (ext != ext_m2)
        pos = jnp.arange(S)[None]                       # [1, S]
        valid_s = pos < (2 * lab_len[:, None] + 1)      # states in range

        alpha0 = jnp.full((B, S), NEG, jnp.float32)
        alpha0 = alpha0.at[:, 0].set(lp_ext[0, :, 0].astype(jnp.float32))
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, lp_ext[0, :, 1].astype(jnp.float32),
                      NEG))

        def lse(*xs):
            stacked = jnp.stack(xs)
            m = jnp.max(stacked, 0)
            return m + jnp.log(jnp.sum(jnp.exp(stacked - m), 0))

        def step(alpha, inp):
            lp_t, t = inp
            a1 = alpha
            a2 = jnp.pad(alpha, ((0, 0), (1, 0)),
                         constant_values=NEG)[:, :S]
            a3 = jnp.where(allow_skip,
                           jnp.pad(alpha, ((0, 0), (2, 0)),
                                   constant_values=NEG)[:, :S], NEG)
            new = lse(a1, a2, a3) + lp_t.astype(jnp.float32)
            new = jnp.where(valid_s, new, NEG)
            # rows past their input length keep their final alpha
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0,
                                (lp_ext[1:], jnp.arange(1, T)))
        # nll = -log(alpha[last blank] + alpha[last label])
        sB = 2 * lab_len                                 # index of last blank
        a_last = jnp.take_along_axis(alpha, sB[:, None], 1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(sB - 1, 0)[:, None], 1)[:, 0]
        a_prev = jnp.where(lab_len > 0, a_prev, NEG)
        nll = -lse(a_last, a_prev)
        if norm_by_times:
            nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return jnp.mean(
                nll / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return run_op("ctc_loss", impl,
                  (log_probs, labels, input_lengths, label_lengths), {})


def soft_margin_loss(input, label, reduction="mean"):
    """log(1 + exp(-label * input)) (reference soft_margin_loss)."""
    def impl(x, y):
        # -log_sigmoid(y*x) == log(1+exp(-y*x)) without the overflow
        return _reduce(-jax.nn.log_sigmoid(y * x), reduction)
    return run_op("soft_margin_loss", impl, (input, label), {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    """Multi-class margin loss (reference multi_margin_loss):
    mean_j max(0, margin - x[y] + x[j])^p, j != y."""
    def impl(x, y, w):
        C = x.shape[1]
        xy = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w is not None:
            m = m * jnp.take(w, y)[:, None]
        mask = jax.nn.one_hot(y, C, dtype=m.dtype)
        return _reduce(((m * (1 - mask)).sum(axis=1)) / C, reduction)
    return run_op("multi_margin_loss", impl, (input, label, weight), {})


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    """Per-class BCE-with-logits averaged over classes (reference
    multi_label_soft_margin_loss)."""
    def impl(x, y, w):
        l = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if w is not None:
            l = l * w
        return _reduce(-l.mean(axis=-1), reduction)
    return run_op("multi_label_soft_margin_loss", impl,
                 (input, label, weight), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    """Gaussian negative log likelihood (reference gaussian_nll_loss)."""
    def impl(mu, y, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            out = out + 0.5 * math.log(2 * math.pi)
        return _reduce(out, reduction)
    return run_op("gaussian_nll_loss", impl, (input, label, variance), {})


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    """Poisson negative log likelihood (reference poisson_nll_loss)."""
    def impl(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return run_op("poisson_nll_loss", impl, (input, label), {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    """Triplet loss with a custom distance callable (reference
    triplet_margin_with_distance_loss)."""
    from .common import pairwise_distance
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    from ...ops import api as _api
    if swap:
        d_neg = _api.minimum(d_neg, dist(positive, negative))
    diff = d_pos - d_neg + margin
    out = _api.maximum(diff, _api.zeros_like(diff))
    if reduction == "mean":
        return _api.mean(out)
    if reduction == "sum":
        return _api.sum(out)
    return out


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean"):
    """RNN-T transducer loss (reference rnnt_loss -> warprnnt op)."""
    from ...ops import api as _api
    out = _api.warprnnt(input, label, input_lengths, label_lengths,
                        blank=blank, fastemit_lambda=fastemit_lambda)
    if reduction == "mean":
        return _api.mean(out)
    if reduction == "sum":
        return _api.sum(out)
    return out


# ---------------------------------------------------------------------------
# round-3 API tail (VERDICT r2 item 5)
# ---------------------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-05, name=None):
    """Dice loss for segmentation (reference: nn/functional/loss.py:48).
    input [N, ..., C] probabilities, label [N, ..., 1] int class ids."""

    def impl(x, lab):
        lab_ = jnp.squeeze(lab, -1)
        onehot = jax.nn.one_hot(lab_, x.shape[-1], dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * onehot, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(onehot, axis=red)
        dice = (2 * inter + epsilon) / (union + epsilon)
        return jnp.mean(1.0 - dice)

    return run_op("dice_loss", impl, (input, label), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference: nn/functional/loss.py:344): L2 reg on
    embeddings + softmax CE over the anchor·positiveᵀ similarity matrix."""

    def impl(a, p, lab):
        lab_ = lab.reshape(-1).astype(jnp.float32)
        same = (lab_[:, None] == lab_[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        sim = a @ p.T
        lp = jax.nn.log_softmax(sim, axis=1)
        ce = jnp.mean(jnp.sum(-tgt * lp, axis=1))
        # reference Beta = 0.25: l2loss = (mean_a + mean_p) * 0.25 * l2_reg
        reg = (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) \
            * (l2_reg * 0.25)
        return ce + reg

    return run_op("npair_loss", impl, (anchor, positive, labels), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py:939,
    phi/kernels/cpu/hsigmoid_loss_kernel.cc).  Default tree = SimpleCode
    (funcs/matrix_bit_code.h:100): class c encodes as ``c + num_classes``;
    node index at bit j is ``(code >> (j+1)) - 1``, branch bit is bit j.
    Matches the reference exactly, including its out-of-path log(2) terms
    (hsigmoid_loss_kernel.cc:95 TODO keeps them in the forward value)."""

    def impl(x, lab, w, b, ptab, pcode):
        lab_ = lab.reshape(-1)
        if ptab is not None:
            codes = pcode.astype(jnp.int32)          # [N, L]
            nodes = ptab.astype(jnp.int32)           # [N, L]
            valid = nodes >= 0
            nodes_safe = jnp.where(valid, nodes, 0)
        else:
            L = max(int(np.floor(np.log2(max(num_classes - 1, 1)))) + 1, 1)
            c = lab_ + num_classes                   # [N]
            bits = jnp.arange(L)
            length = jnp.floor(
                jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
            valid = bits[None, :] < length[:, None]
            nodes = (c[:, None] >> (bits[None, :] + 1)) - 1
            codes = (c[:, None] >> bits[None, :]) & 1
            nodes_safe = jnp.where(valid, nodes, 0)
        wsel = jnp.take(w, nodes_safe, axis=0)       # [N, L, D]
        pre = jnp.einsum("nd,nld->nl", x, wsel)
        if b is not None:
            pre = pre + jnp.take(b.reshape(-1), nodes_safe)
        pre = jnp.clip(pre, -40.0, 40.0)
        pre = jnp.where(valid, pre, 0.0)
        # softrelu CE: sum log(1+e^pre) - sum_{bit=1} pre  (kernel :91-99)
        loss = jnp.sum(jnp.log1p(jnp.exp(pre)), axis=1) \
            - jnp.sum(jnp.where(valid & (codes > 0), pre, 0.0), axis=1)
        return loss[:, None]

    return run_op("hsigmoid_loss", impl,
                  (input, label, weight, bias, path_table, path_code), {})


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax CE (reference:
    nn/functional/loss.py:2236, phi margin_cross_entropy kernel).

    ``logits`` are cosines from normalized features × normalized weights.
    The target logit θ is re-margined: cos(m1·θ + m2) − m3, then scaled.
    Class-parallel (model-parallel) operation: when called inside a
    ``shard_map`` region with the classes dim sharded, pass the mesh axis
    name via ``group`` (str) — max/sum reductions then ride ``psum`` the
    way the reference reduces over the mp ProcessGroup."""
    axis_name = None
    if isinstance(group, str):
        axis_name = group
    elif group is not None and group is not False:
        axis_name = getattr(group, "axis_name", None)

    def impl(lg, lab):
        lab_ = lab.reshape(-1)
        n = lg.shape[0]
        local_c = lg.shape[1]
        if axis_name is not None:
            idx = jax.lax.axis_index(axis_name)
            class_start = idx * local_c
        else:
            class_start = 0
        local_lab = lab_ - class_start
        in_range = (local_lab >= 0) & (local_lab < local_c)
        safe = jnp.where(in_range, local_lab, 0)
        cos = jnp.clip(
            jnp.take_along_axis(lg, safe[:, None], axis=1)[:, 0], -1.0, 1.0)
        theta = jnp.arccos(cos)
        re_margined = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(safe, local_c, dtype=lg.dtype) \
            * in_range[:, None].astype(lg.dtype)
        mod = lg * (1 - onehot) + re_margined[:, None] * onehot
        mod = mod * scale
        mx = jnp.max(mod, axis=1)
        if axis_name is not None:
            mx = jax.lax.pmax(mx, axis_name)
        e = jnp.exp(mod - mx[:, None])
        denom = jnp.sum(e, axis=1)
        if axis_name is not None:
            denom = jax.lax.psum(denom, axis_name)
        softmax = e / denom[:, None]
        tgt_logit = jnp.where(in_range, re_margined * scale, 0.0)
        if axis_name is not None:
            tgt_logit = jax.lax.psum(tgt_logit, axis_name)
        loss = jnp.log(denom) + mx - tgt_logit
        loss = loss[:, None]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        return (loss, softmax)

    loss, softmax = run_op("margin_cross_entropy", impl, (logits, label), {})
    if return_softmax:
        return loss, softmax
    return loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference: nn/functional/loss.py:4473; Grave et al.
    2016).  head covers [0, cutoffs[0]) + one logit per tail cluster; each
    tail cluster i covers [cutoffs[i], cutoffs[i+1]) through a low-rank
    two-matmul projection."""
    cutoffs = [int(c) for c in cutoffs]
    shortlist = cutoffs[0]

    flat_tails = []
    for pair in tail_weights:
        flat_tails.extend(list(pair))

    def impl(x, lab, hw, hb, *tails):
        lab_ = lab.reshape(-1).astype(jnp.int32)
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        n_cl = len(tails) // 2
        # shortlist hit: logprob directly from head
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lab_, 0, shortlist - 1)[:, None], axis=1)[:, 0]
        for i in range(n_cl):
            lo = cutoffs[i]
            proj, cls = tails[2 * i], tails[2 * i + 1]
            hi = lo + cls.shape[1]
            in_cluster = (lab_ >= lo) & (lab_ < hi)
            rel = jnp.clip(lab_ - lo, 0, cls.shape[1] - 1)
            tail_lp = jax.nn.log_softmax((x @ proj) @ cls, axis=-1)
            cluster_lp = head_lp[:, shortlist + i] + jnp.take_along_axis(
                tail_lp, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_cluster, cluster_lp, out)
        loss = -jnp.mean(out)
        return (out, loss)

    out, loss = run_op("adaptive_log_softmax_with_loss", impl,
                       (input, label, head_weight, head_bias, *flat_tails),
                       {})
    return out, loss
