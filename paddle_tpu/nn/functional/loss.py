"""Loss functional ops (reference: python/paddle/nn/functional/loss.py →
phi cross_entropy/... kernels).  softmax+CE fuses in XLA; the TP-sharded
variant (ParallelCrossEntropy) lives in parallel/mp_layers."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    def impl(logits, lab, w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
            jnp.log(jnp.clip(logits, 1e-30, None))
        n_cls = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            tgt = lab
            loss = -jnp.sum(tgt * lp, axis=axis)
            valid = jnp.ones(loss.shape, bool)
        else:
            lab_ = lab
            if lab_.ndim == logits.ndim:
                lab_ = jnp.squeeze(lab_, axis)
            valid = lab_ != ignore_index
            safe = jnp.where(valid, lab_, 0)
            if label_smoothing > 0.0:
                onehot = jax.nn.one_hot(safe, n_cls, dtype=lp.dtype, axis=axis)
                tgt = onehot * (1 - label_smoothing) + label_smoothing / n_cls
                loss = -jnp.sum(tgt * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.squeeze(loss, axis)
            if w is not None:
                loss = loss * w[safe]
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if w is not None and not soft_label:
                lab_ = lab if lab.ndim < logits.ndim else jnp.squeeze(lab, axis)
                safe = jnp.where(valid, lab_, 0)
                denom = jnp.sum(jnp.where(valid, w[safe], 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(lp.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return run_op("cross_entropy", impl, (input, label, weight), {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    out = cross_entropy(logits, label, soft_label=soft_label,
                        ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from ...ops import api as _api
        return out, _api.softmax(logits, axis=axis)
    return out


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    def impl(lp, lab, w):
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        loss = -jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0] \
            if lp.ndim == 2 else -jnp.take_along_axis(
                lp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        if w is not None:
            loss = loss * w[safe]
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(w[safe] * valid) if w is not None else \
                jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return run_op("nll_loss", impl, (input, label, weight), {})


def mse_loss(input, label, reduction="mean"):
    return run_op("mse_loss", lambda x, y: _reduce(jnp.square(x - y),
                                                   reduction),
                  (input, label), {})


def l1_loss(input, label, reduction="mean"):
    return run_op("l1_loss", lambda x, y: _reduce(jnp.abs(x - y), reduction),
                  (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def impl(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return run_op("smooth_l1_loss", impl, (input, label), {})


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def impl(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return run_op("huber_loss", impl, (input, label), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    def impl(p, y, w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return run_op("binary_cross_entropy", impl, (input, label, weight), {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    def impl(z, y, w, pw):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            base = base * w
        return _reduce(base, reduction)

    return run_op("bce_with_logits", impl, (logit, label, weight, pos_weight),
                  {})


def kl_div(input, label, reduction="mean", log_target=False):
    def impl(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-30, None)) - lp),
                             0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return run_op("kl_div", impl, (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def impl(x1, x2, y):
        cos = jnp.sum(x1 * x2, -1) / (
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return run_op("cosine_embedding_loss", impl, (input1, input2, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    def impl(x, o, y):
        return _reduce(jnp.maximum(0.0, -y * (x - o) + margin), reduction)

    return run_op("margin_ranking_loss", impl, (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def impl(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)

    return run_op("hinge_embedding_loss", impl, (input, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def impl(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p),
                                     -1), 1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)

    return run_op("triplet_margin_loss", impl, (input, positive, negative), {})


def label_smooth(label, prior_dist=None, epsilon=0.1):
    def impl(y, pd):
        n = y.shape[-1]
        if pd is not None:
            return (1 - epsilon) * y + epsilon * pd
        return (1 - epsilon) * y + epsilon / n

    return run_op("label_smooth", impl, (label, prior_dist), {})


def square_error_cost(input, label):
    return run_op("square_error_cost", lambda x, y: jnp.square(x - y),
                  (input, label), {})


def log_loss(input, label, epsilon=1e-4):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log1p(epsilon - p)

    return run_op("log_loss", impl, (input, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def impl(z, y, nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if nrm is not None:
            loss = loss / nrm
        return _reduce(loss, reduction)

    return run_op("sigmoid_focal_loss", impl, (logit, label, normalizer), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist temporal classification loss.

    Reference: python/paddle/nn/functional/loss.py ``ctc_loss`` backed by
    warpctc (phi/kernels/impl/warpctc_kernel_impl.h).  TPU-native: the
    standard log-space forward algorithm as one ``lax.scan`` over time —
    static shapes, fully batched, differentiable by autodiff (no
    hand-written warpctc gradient needed).

    log_probs: [T, B, C] (log-softmaxed); labels: [B, L] int; returns per
    paddle semantics (reduction "mean" divides by label_lengths first).
    """
    NEG = -1e30

    def impl(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        lab = lab.astype(jnp.int32)
        in_len = in_len.reshape(B).astype(jnp.int32)
        lab_len = lab_len.reshape(B).astype(jnp.int32)
        # extended label sequence: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        # emission log-probs for the extended sequence: [T, B, S]
        lp_ext = jnp.take_along_axis(
            lp, jnp.broadcast_to(ext[None], (T, B, S)), axis=2)
        # transition mask: s -> s allowed from s-2 when ext[s] != blank and
        # ext[s] != ext[s-2]
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
        allow_skip = (ext != blank) & (ext != ext_m2)
        pos = jnp.arange(S)[None]                       # [1, S]
        valid_s = pos < (2 * lab_len[:, None] + 1)      # states in range

        alpha0 = jnp.full((B, S), NEG, jnp.float32)
        alpha0 = alpha0.at[:, 0].set(lp_ext[0, :, 0].astype(jnp.float32))
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, lp_ext[0, :, 1].astype(jnp.float32),
                      NEG))

        def lse(*xs):
            stacked = jnp.stack(xs)
            m = jnp.max(stacked, 0)
            return m + jnp.log(jnp.sum(jnp.exp(stacked - m), 0))

        def step(alpha, inp):
            lp_t, t = inp
            a1 = alpha
            a2 = jnp.pad(alpha, ((0, 0), (1, 0)),
                         constant_values=NEG)[:, :S]
            a3 = jnp.where(allow_skip,
                           jnp.pad(alpha, ((0, 0), (2, 0)),
                                   constant_values=NEG)[:, :S], NEG)
            new = lse(a1, a2, a3) + lp_t.astype(jnp.float32)
            new = jnp.where(valid_s, new, NEG)
            # rows past their input length keep their final alpha
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0,
                                (lp_ext[1:], jnp.arange(1, T)))
        # nll = -log(alpha[last blank] + alpha[last label])
        sB = 2 * lab_len                                 # index of last blank
        a_last = jnp.take_along_axis(alpha, sB[:, None], 1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(sB - 1, 0)[:, None], 1)[:, 0]
        a_prev = jnp.where(lab_len > 0, a_prev, NEG)
        nll = -lse(a_last, a_prev)
        if norm_by_times:
            nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return jnp.mean(
                nll / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return run_op("ctc_loss", impl,
                  (log_probs, labels, input_lengths, label_lengths), {})


def soft_margin_loss(input, label, reduction="mean"):
    """log(1 + exp(-label * input)) (reference soft_margin_loss)."""
    def impl(x, y):
        # -log_sigmoid(y*x) == log(1+exp(-y*x)) without the overflow
        return _reduce(-jax.nn.log_sigmoid(y * x), reduction)
    return run_op("soft_margin_loss", impl, (input, label), {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    """Multi-class margin loss (reference multi_margin_loss):
    mean_j max(0, margin - x[y] + x[j])^p, j != y."""
    def impl(x, y, w):
        C = x.shape[1]
        xy = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w is not None:
            m = m * jnp.take(w, y)[:, None]
        mask = jax.nn.one_hot(y, C, dtype=m.dtype)
        return _reduce(((m * (1 - mask)).sum(axis=1)) / C, reduction)
    return run_op("multi_margin_loss", impl, (input, label, weight), {})


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    """Per-class BCE-with-logits averaged over classes (reference
    multi_label_soft_margin_loss)."""
    def impl(x, y, w):
        l = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if w is not None:
            l = l * w
        return _reduce(-l.mean(axis=-1), reduction)
    return run_op("multi_label_soft_margin_loss", impl,
                 (input, label, weight), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    """Gaussian negative log likelihood (reference gaussian_nll_loss)."""
    def impl(mu, y, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            out = out + 0.5 * math.log(2 * math.pi)
        return _reduce(out, reduction)
    return run_op("gaussian_nll_loss", impl, (input, label, variance), {})


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    """Poisson negative log likelihood (reference poisson_nll_loss)."""
    def impl(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return run_op("poisson_nll_loss", impl, (input, label), {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    """Triplet loss with a custom distance callable (reference
    triplet_margin_with_distance_loss)."""
    from .common import pairwise_distance
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    from ...ops import api as _api
    if swap:
        d_neg = _api.minimum(d_neg, dist(positive, negative))
    diff = d_pos - d_neg + margin
    out = _api.maximum(diff, _api.zeros_like(diff))
    if reduction == "mean":
        return _api.mean(out)
    if reduction == "sum":
        return _api.sum(out)
    return out


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean"):
    """RNN-T transducer loss (reference rnnt_loss -> warprnnt op)."""
    from ...ops import api as _api
    out = _api.warprnnt(input, label, input_lengths, label_lengths,
                        blank=blank, fastemit_lambda=fastemit_lambda)
    if reduction == "mean":
        return _api.mean(out)
    if reduction == "sum":
        return _api.sum(out)
    return out
