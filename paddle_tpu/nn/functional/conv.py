"""Convolution functional ops.

Reference: python/paddle/nn/functional/conv.py → phi conv kernels (gpudnn).
TPU-native: ``lax.conv_general_dilated`` lowers directly onto the MXU; no
cudnn autotuning layer is needed (XLA picks the layout).  Weight layout
follows paddle: [out_c, in_c/groups, *spatial].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op


def _tupleize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pad_spec(padding, n, strides, in_spatial, k_spatial, dilation):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [tuple(int(x) for x in p) for p in padding]


def _conv_impl(x, weight, bias, stride, padding, dilation, groups,
               data_format, n):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    sp = "".join("DHW"[3 - n:])
    if channel_last:
        dn_in = "N" + sp + "C"
    else:
        dn_in = "NC" + sp
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (dn_in, "OI" + sp, dn_in))
    in_spatial = [x.shape[i] for i in range(1, n + 1)] if channel_last else \
        [x.shape[i] for i in range(2, n + 2)]
    pad = _pad_spec(padding, n, stride, in_spatial, weight.shape[2:], dilation)
    # NOTE: no preferred_element_type here — the TPU MXU accumulates bf16
    # convs in fp32 natively, and jax's conv transpose rule emits a
    # mixed-dtype conv (bf16 activations x fp32 cotangent) when the flag
    # is set, breaking grad-of-conv under AMP.
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=_tupleize(stride, n),
        padding=pad,
        rhs_dilation=_tupleize(dilation, n),
        feature_group_count=groups,
        dimension_numbers=dn,
    )
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    if bias is not None:
        if channel_last:
            out = out + jnp.reshape(bias, (1,) * (n + 1) + (-1,))
        else:
            out = out + jnp.reshape(bias, (1, -1) + (1,) * n)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return run_op("conv1d", lambda x, w, b: _conv_impl(
        x, w, b, stride, padding, dilation, groups, data_format, 1),
        (x, weight, bias), {})


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return run_op("conv2d", lambda x, w, b: _conv_impl(
        x, w, b, stride, padding, dilation, groups, data_format, 2),
        (x, weight, bias), {})


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return run_op("conv3d", lambda x, w, b: _conv_impl(
        x, w, b, stride, padding, dilation, groups, data_format, 3),
        (x, weight, bias), {})



def _add_channel_bias(out, bias, channel_last, n):
    if bias is None:
        return out
    if channel_last:
        return out + jnp.reshape(bias, (1,) * (n + 1) + (-1,))
    return out + jnp.reshape(bias, (1, -1) + (1,) * n)

def _conv_transpose_impl(x, weight, bias, stride, padding, output_padding,
                         dilation, groups, data_format, n):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    sp = "".join("DHW"[3 - n:])
    dn_in = ("N" + sp + "C") if channel_last else ("NC" + sp)
    if groups != 1:
        # lax.conv_transpose has no feature_group_count: run each group's
        # transpose conv separately (groups is small and static — the
        # unrolled concat fuses fine under XLA)
        ic = x.shape[-1] if channel_last else x.shape[1]
        icg = ic // groups
        outs = []
        for g in range(groups):
            xs = (x[..., g * icg:(g + 1) * icg] if channel_last
                  else x[:, g * icg:(g + 1) * icg])
            ws = weight[g * icg:(g + 1) * icg]
            outs.append(_conv_transpose_impl(
                xs, ws, None, stride, padding, output_padding, dilation, 1,
                data_format, n))
        out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        return _add_channel_bias(out, bias, channel_last, n)
    # paddle transpose-conv weight layout [in_c, out_c/groups, *spatial];
    # with transpose_kernel=True lax swaps I/O, so declare it as "OI".
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (dn_in, "OI" + sp, dn_in))
    strides = _tupleize(stride, n)
    dil = _tupleize(dilation, n)
    k_spatial = weight.shape[2:]
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tupleize(padding, n) if not isinstance(padding, (list,)) or all(
            isinstance(v, (int, np.integer)) for v in padding) else padding
        if isinstance(p, tuple):
            # paddle pad p → lax pad (k_eff-1-p) so output = (in-1)*s - 2p + k
            pad = []
            for i, v in enumerate(p):
                k_eff = (k_spatial[i] - 1) * dil[i] + 1
                pad.append((k_eff - 1 - v, k_eff - 1 - v))
        else:
            pad = p
    out = jax.lax.conv_transpose(
        x, weight, strides=strides, padding=pad, rhs_dilation=dil,
        dimension_numbers=dn, transpose_kernel=True)
    opad = _tupleize(output_padding, n) if output_padding else (0,) * n
    if any(opad):
        widths = [(0, 0)] * out.ndim
        for i, o in enumerate(opad):
            ax = (1 + i) if channel_last else (2 + i)
            widths[ax] = (0, o)
        out = jnp.pad(out, widths)
    out = _add_channel_bias(out, bias, channel_last, n)
    return out


def _resolve_output_size(x, weight, stride, padding, output_padding,
                         dilation, output_size, data_format, n):
    """Reference F.conv*_transpose ``output_size``: the transpose-conv
    output length is ambiguous by up to stride-1; output_size picks one
    by deriving the per-dim output_padding."""
    if output_size is None:
        return output_padding
    st = _tupleize(stride, n)
    di = _tupleize(dilation, n)
    os_ = _tupleize(output_size, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    in_sp = x.shape[1:1 + n] if channel_last else x.shape[2:2 + n]
    k_sp = weight.shape[2:]

    pd = padding
    if isinstance(pd, str):
        up = pd.upper()
        if up == "SAME":
            # transpose-conv SAME: out = in * stride
            pd = None
            bases = [int(in_sp[i]) * st[i] for i in range(n)]
        else:                          # VALID: zero pads
            pd = [(0, 0)] * n
    if pd is not None:
        if isinstance(pd, (int, np.integer)):
            pd = [(int(pd), int(pd))] * n
        elif isinstance(pd, (list, tuple)) and len(pd) == n and all(
                isinstance(p, (int, np.integer)) for p in pd):
            pd = [(int(p), int(p)) for p in pd]
        elif isinstance(pd, (list, tuple)) and len(pd) == 2 * n and all(
                isinstance(p, (int, np.integer)) for p in pd):
            pd = [(int(pd[2 * i]), int(pd[2 * i + 1]))
                  for i in range(n)]
        elif isinstance(pd, (list, tuple)) and len(pd) == n + 2:
            # full-dim pair list incl. batch/channel: slice the SPATIAL
            # entries per data_format
            sp = pd[1:1 + n] if channel_last else pd[2:2 + n]
            pd = [(int(p[0]), int(p[1])) for p in sp]
        else:
            pd = [(int(p[0]), int(p[1])) for p in pd]
        bases = [
            (int(in_sp[i]) - 1) * st[i] - pd[i][0] - pd[i][1]
            + di[i] * (int(k_sp[i]) - 1) + 1
            for i in range(n)]
    out_pad = []
    for i in range(n):
        op = int(os_[i]) - bases[i]
        if not 0 <= op < st[i]:
            raise ValueError(
                f"output_size[{i}]={os_[i]} unreachable (base "
                f"{bases[i]}, stride {st[i]}: valid range "
                f"[{bases[i]}, {bases[i] + st[i] - 1}])")
        out_pad.append(op)
    return tuple(out_pad)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL", *, output_size=None):
    output_padding = _resolve_output_size(
        x, weight, stride, padding, output_padding, dilation, output_size,
        data_format, 1)
    return run_op("conv1d_transpose", lambda x, w, b: _conv_transpose_impl(
        x, w, b, stride, padding, output_padding, dilation, groups,
        data_format, 1), (x, weight, bias), {})


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", *, output_size=None):
    output_padding = _resolve_output_size(
        x, weight, stride, padding, output_padding, dilation, output_size,
        data_format, 2)
    return run_op("conv2d_transpose", lambda x, w, b: _conv_transpose_impl(
        x, w, b, stride, padding, output_padding, dilation, groups,
        data_format, 2), (x, weight, bias), {})


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", *, output_size=None):
    output_padding = _resolve_output_size(
        x, weight, stride, padding, output_padding, dilation, output_size,
        data_format, 3)
    return run_op("conv3d_transpose", lambda x, w, b: _conv_transpose_impl(
        x, w, b, stride, padding, output_padding, dilation, groups,
        data_format, 3), (x, weight, bias), {})
