"""paddle.nn.utils parity (reference python/paddle/nn/utils/:
weight_norm_hook.py, spectral_norm_hook.py, clip_grad_norm_.py,
clip_grad_value_.py, transform_parameters.py).

TPU-first shape: the reparametrizations are forward-PRE-hooks that
recompute the derived weight from the decomposed Parameters each call —
the tape differentiates straight through the recompute (the reference
needs dedicated hook classes wrapping C++ norm ops)."""

from __future__ import annotations

from typing import Iterable, List

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor
from ...ops import api as _api

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


# ---------------------------------------------------------------------------
# grad clipping (in-place over .grad)
# ---------------------------------------------------------------------------

def clip_grad_norm_(parameters, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Scale all grads so their GLOBAL norm is <= max_norm (reference
    clip_grad_norm_.py); returns the pre-clip total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0, jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"grad norm is non-finite ({float(total)}); gradients cannot "
            "be clipped (error_if_nonfinite=True)")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._value = (g._value.astype(jnp.float32) * scale).astype(
            g._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value: float):
    """Clamp every grad element into [-clip_value, clip_value]
    (reference clip_grad_value_.py)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    cv = abs(float(clip_value))
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -cv, cv)


# ---------------------------------------------------------------------------
# parameter <-> flat vector (reference transform_parameters.py)
# ---------------------------------------------------------------------------

def parameters_to_vector(parameters, name=None) -> Tensor:
    vals = [jnp.reshape(p._value, (-1,)) for p in parameters]
    return Tensor(jnp.concatenate(vals) if vals
                  else jnp.zeros((0,), jnp.float32))


def vector_to_parameters(vec: Tensor, parameters, name=None) -> None:
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p._value.shape)) if p._value.shape else 1
        chunk = v[off:off + n].reshape(p._value.shape).astype(
            p._value.dtype)
        p.set_value(chunk)
        off += n


# ---------------------------------------------------------------------------
# weight norm (reference weight_norm_hook.py)
# ---------------------------------------------------------------------------

def _norm_except_dim(w, dim: int):
    axes = tuple(i for i in range(len(w.shape)) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparametrize ``layer.<name>`` as g * v/||v|| (Salimans & Kingma
    2016): the optimizer sees ``<name>_g``/``<name>_v``; a pre-forward
    hook recomputes the derived weight, and the tape differentiates
    through the recompute."""
    w = getattr(layer, name)
    if dim is None:
        dim = -1          # treat whole tensor as one group
    wv = jnp.asarray(w._value)
    if dim == -1:
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv)))
        g_shape = ()
    else:
        g0 = _norm_except_dim(wv, dim)
        g_shape = g0.shape
    g = Parameter(g0.astype(wv.dtype), name=f"{w.name}_g")
    v = Parameter(wv, name=f"{w.name}_v")
    layer._parameters.pop(name, None)
    object.__setattr__(layer, name, w)      # placeholder until first fwd
    setattr(layer, f"{name}_g", g)
    setattr(layer, f"{name}_v", v)

    def _recompute(lyr, _inputs):
        vv = getattr(lyr, f"{name}_v")
        gg = getattr(lyr, f"{name}_g")
        if dim == -1:
            norm = _api.sqrt(_api.sum(_api.square(vv)))
        else:
            axes = [i for i in range(len(vv.shape)) if i != dim]
            norm = _api.sqrt(_api.sum(_api.square(vv), axis=axes,
                                      keepdim=True))
        object.__setattr__(lyr, name, vv / norm * gg)
        return None

    helper = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (helper, dim)
    _recompute(layer, ())                   # materialize immediately
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Bake the current derived weight back into a plain Parameter and
    drop the g/v decomposition (reference remove_weight_norm)."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    helper, dim = hooks.pop(name)
    helper.remove()
    derived = getattr(layer, name)
    for suffix in ("_g", "_v"):
        layer._parameters.pop(name + suffix, None)
        if hasattr(layer, name + suffix):
            object.__delattr__(layer, name + suffix)
    setattr(layer, name, Parameter(jnp.asarray(derived._value)))
    return layer


# ---------------------------------------------------------------------------
# spectral norm (reference spectral_norm_hook.py)
# ---------------------------------------------------------------------------

def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim=None):
    """Divide the weight by its largest singular value, estimated by
    power iteration on persistent u/v buffers (Miyato et al. 2018)."""
    w = getattr(layer, name)
    wv = jnp.asarray(w._value)
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    h, wdim = mat.shape
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(h).astype(np.float32)
    v0 = rng.standard_normal(wdim).astype(np.float32)
    orig = Parameter(wv, name=f"{w.name}_orig")
    layer._parameters.pop(name, None)
    setattr(layer, f"{name}_orig", orig)
    layer.register_buffer(f"{name}_u",
                          Tensor(u0 / (np.linalg.norm(u0) + eps)))
    layer.register_buffer(f"{name}_v",
                          Tensor(v0 / (np.linalg.norm(v0) + eps)))

    def _recompute(lyr, _inputs):
        ww = getattr(lyr, f"{name}_orig")
        m = jnp.moveaxis(jnp.asarray(ww._value), dim, 0).reshape(h, -1)
        u = jnp.asarray(getattr(lyr, f"{name}_u")._value)
        v = jnp.asarray(getattr(lyr, f"{name}_v")._value)
        for _ in range(max(1, n_power_iterations)):
            v = m.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = m @ v
            u = u / (jnp.linalg.norm(u) + eps)
        getattr(lyr, f"{name}_u")._value = u
        getattr(lyr, f"{name}_v")._value = v
        sigma = u @ (m @ v)
        # divide the LIVE Parameter so grads flow to weight_orig; sigma
        # is a stop-gradient estimate (reference detaches u/v too)
        object.__setattr__(lyr, name,
                           ww / Tensor(jnp.maximum(sigma, eps)))
        return None

    helper = layer.register_forward_pre_hook(_recompute)
    layer._spectral_norm_hooks = getattr(layer, "_spectral_norm_hooks",
                                         {})
    layer._spectral_norm_hooks[name] = helper
    _recompute(layer, ())
    return layer
