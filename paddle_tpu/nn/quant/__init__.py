"""``paddle_tpu.nn.quant`` — weight-only quantization.

Reference: python/paddle/nn/quant/quantized_linear.py (``weight_quantize``,
``weight_dequantize``, ``weight_only_linear``, ``llm_int8_linear``) backed
by phi/kernels/weight_only_linear_kernel.h + fusion/cutlass gemms.

Layout note: the reference's weight_quantize returns a CUTLASS-tiled
layout; here the quantized weight keeps the LOGICAL [in, out] layout of
``paddle_tpu.nn.Linear`` (the Pallas kernel does its own tiling), so
quantized checkpoints are human-readable and resharding-friendly.

int4 is stored two nibbles per int8 byte along the input dim (rows 2k and
2k+1 packed), halving HBM again; the unpack happens at dequant.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.dispatch import run_op
from ...core.tensor import Tensor

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def _unwrap(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def _group_expand(scale, K, group_size):
    """[G, N] group scales -> [K, N] per-row scales."""
    s = jnp.repeat(scale, group_size, axis=0)
    return s[:K]


def weight_quantize(x, algo: str = "weight_only_int8", arch=None,
                    group_size: int = -1):
    """Absmax quantization.  Returns (out, scale).

    algo: "weight_only_int8" | "llm.int8" -> int8 [K, N];
          "weight_only_int4" -> packed int8 [ceil(K/2), N] (two rows per
          byte: low nibble = even row, high nibble = odd row).

    group_size: -1 = one scale per output channel (scale [N]); 64/128 =
    group-wise — one scale per (group of input rows x output channel)
    (scale [ceil(K/group_size), N], the reference weight_quantize's
    group_size semantics).
    """
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unknown quantize algo {algo!r}")
    if group_size not in (-1, None, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")
    grouped = group_size in (64, 128)
    if grouped and algo == "llm.int8":
        # llm_int8_linear's vector-wise int8 dot consumes a [N] scale;
        # grouped scales belong to the weight_only_* paths
        raise ValueError("group_size is only supported for "
                         "weight_only_int8/int4, not llm.int8")

    def impl(w):
        wf = w.astype(jnp.float32)
        K = wf.shape[0]
        if grouped:
            G = -(-K // group_size)
            wp = jnp.pad(wf, ((0, G * group_size - K), (0, 0)))
            absmax = jnp.max(jnp.abs(wp.reshape(G, group_size, -1)), axis=1)
        else:
            absmax = jnp.max(jnp.abs(wf), axis=0)
        qmax = 7.0 if algo == "weight_only_int4" else 127.0
        scale = jnp.maximum(absmax, 1e-8) / qmax
        srow = _group_expand(scale, K, group_size) if grouped else scale
        q = jnp.clip(jnp.round(wf / srow), -qmax - 1, qmax).astype(jnp.int8)
        if algo != "weight_only_int4":
            return q, scale
        if q.shape[0] % 2:
            q = jnp.pad(q, ((0, 1), (0, 0)))
        half = q.shape[0] // 2
        # HALVES packing: rows [0, K/2) in the low nibble, rows
        # [K/2, K) in the high nibble — lets the matmul kernel unpack
        # as two contiguous nibble-plane matmuls (x_lo @ lo + x_hi @ hi)
        # with no row interleave.
        lo = q[:half]
        hi = q[half:]
        packed = (lo & 0x0F) | (hi << 4)
        return packed.astype(jnp.int8), scale

    return run_op("weight_quantize", impl, (x,), {}, differentiable=False)


def _unpack_int4(packed, k_orig):
    lo = (packed << 4).astype(jnp.int8) >> 4       # sign-extend low nibble
    hi = packed >> 4                               # arithmetic shift
    q = jnp.concatenate([lo, hi], axis=0)          # halves packing
    return q[:k_orig]


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float32", k: Optional[int] = None,
                      group_size: int = -1):
    """Inverse of :func:`weight_quantize` (reference weight_dequantize),
    incl. group-wise scales ([G, N] with ``group_size`` rows/group)."""
    if group_size not in (-1, None, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")
    grouped = group_size in (64, 128)

    def impl(q, s):
        if algo == "weight_only_int4":
            kk = k if k is not None else q.shape[0] * 2
            qq = _unpack_int4(q, kk)
        else:
            qq = q
        sf = s.astype(jnp.float32)
        if grouped:
            sf = _group_expand(sf, qq.shape[0], group_size)
        return (qq.astype(jnp.float32) * sf).astype(jnp.dtype(out_dtype))

    return run_op("weight_dequantize", impl, (x, scale), {},
                  differentiable=False)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """y = x @ dequant(weight) + bias (reference
    nn/quant/quantized_linear.py:weight_only_linear).

    weight: int8 [K, N] ("int8") or packed int4 [ceil(K/2), N] ("int4").
    Dispatches to the Pallas streaming-dequant matmul on TPU
    (ops/pallas/quant_linear.py); jnp dequant+matmul elsewhere.
    """
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8/int4, got "
                         f"{weight_dtype!r}")
    if weight_scale is None:
        raise ValueError("weight_only_linear needs weight_scale from "
                         "weight_quantize")
    if group_size not in (-1, None, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")
    grouped = group_size in (64, 128)

    def impl(xv, wq, s, b):
        K = xv.shape[-1]
        try:
            on_tpu = jax.devices()[0].platform.lower() in ("tpu", "axon")
        except Exception:
            on_tpu = False
        from ...core.flags import FLAGS
        # the int4 grouped kernel needs nibble planes aligned to groups
        int4_ok = (not grouped) or (wq.shape[0] % group_size == 0)
        if (on_tpu or FLAGS.pallas_interpret) and \
                (weight_dtype == "int8" or int4_ok):
            gs = group_size if grouped else -1
            if weight_dtype == "int4":
                # packed nibbles stream straight into the kernel — half
                # the HBM bytes of int8; unpack happens in VMEM
                from ...ops.pallas.quant_linear import (
                    weight_only_matmul_int4)
                y = weight_only_matmul_int4(xv, wq, s, group_size=gs)
            else:
                from ...ops.pallas.quant_linear import weight_only_matmul
                y = weight_only_matmul(xv, wq, s, group_size=gs)
        else:
            wd = _unpack_int4(wq, K) if weight_dtype == "int4" else wq
            sf = s.astype(xv.dtype)
            if grouped:
                y = xv @ (wd.astype(xv.dtype)
                          * _group_expand(sf, wd.shape[0], group_size))
            else:
                y = (xv @ wd.astype(xv.dtype)) * sf
        if b is not None:
            y = y + b
        return y

    return run_op("weight_only_linear", impl, (x, weight, weight_scale,
                                               bias), {})


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """LLM.int8() mixed decomposition (reference llm_int8_linear):
    outlier activation columns (|x| > threshold) run in fp, the rest on
    the int8 weight path, summed."""
    if weight_scale is None:
        raise ValueError("llm_int8_linear needs weight_scale")

    def impl(xv, wq, s, b):
        wf = wq.astype(jnp.float32) * s.astype(jnp.float32)
        col_amax = jnp.max(jnp.abs(xv.astype(jnp.float32)), axis=tuple(
            range(xv.ndim - 1)))
        outlier = col_amax > threshold                     # [K]
        x_in = jnp.where(outlier, 0.0, xv.astype(jnp.float32))
        # inlier path: quantize activations to int8 per-row absmax and run
        # an integer dot (LLM.int8()'s vector-wise scheme); outliers stay fp
        row_amax = jnp.max(jnp.abs(x_in), axis=-1, keepdims=True)
        xs = jnp.maximum(row_amax, 1e-8) / 127.0
        x8 = jnp.clip(jnp.round(x_in / xs), -127, 127).astype(jnp.int8)
        y_in = jax.lax.dot_general(
            x8, wq, (((x8.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        y_in = y_in * xs * s.astype(jnp.float32)
        x_out = jnp.where(outlier, xv.astype(jnp.float32), 0.0)
        y = y_in + (x_out @ wf)
        if b is not None:
            y = y + b
        return y.astype(xv.dtype)

    return run_op("llm_int8_linear", impl, (x, weight, weight_scale, bias),
                  {})


# ---------------------------------------------------------------------------
# fp8 gemm (reference paddle/phi/kernels/fusion/fp8_gemm/ +
# incubate fp8_fp8_half_gemm_fused): e4m3 storage with per-tensor scales,
# MXU matmul in fp8 with fp32 accumulation.
# ---------------------------------------------------------------------------
_FP8_E4M3_MAX = 448.0


def quantize_to_fp8(x, dtype="float8_e4m3fn"):
    """Per-tensor absmax scaling into fp8.  Returns (x_fp8, scale) with
    ``x ≈ x_fp8.astype(f32) * scale``."""
    from ...core.dispatch import run_op

    def impl(xv):
        absmax = jnp.max(jnp.abs(xv.astype(jnp.float32)))
        scale = jnp.maximum(absmax, 1e-12) / _FP8_E4M3_MAX
        q = (xv.astype(jnp.float32) / scale).astype(jnp.dtype(dtype))
        return q, scale

    return run_op("quantize_to_fp8", impl, (x,), {}, differentiable=False)


def fp8_gemm(x, y, x_scale=None, y_scale=None, bias=None,
             transpose_x=False, transpose_y=False, activation=None,
             output_dtype="float32"):
    """out = act((x_fp8 @ y_fp8) * x_scale * y_scale + bias) (reference
    fp8_fp8_half_gemm_fused).  Inputs may be pre-quantized fp8 (+ scales)
    or float tensors (quantized here).  The dot runs in fp8 with fp32
    accumulation — the MXU's native fp8 path on v5p+; elsewhere XLA
    emulates, numerics identical."""
    from ...core.dispatch import run_op

    def impl(xv, yv, xs, ys, b):
        def prep(v, s):
            if v.dtype in (jnp.float8_e4m3fn, jnp.float8_e5m2):
                return v, (jnp.asarray(1.0, jnp.float32) if s is None
                           else s.astype(jnp.float32))
            absmax = jnp.max(jnp.abs(v.astype(jnp.float32)))
            sc = jnp.maximum(absmax, 1e-12) / _FP8_E4M3_MAX
            return ((v.astype(jnp.float32) / sc).astype(jnp.float8_e4m3fn),
                    sc)

        xq, xsc = prep(xv, xs)
        yq, ysc = prep(yv, ys)
        if transpose_x:
            xq = jnp.swapaxes(xq, -1, -2)
        if transpose_y:
            yq = jnp.swapaxes(yq, -1, -2)
        out = jax.lax.dot_general(
            xq, yq, (((xq.ndim - 1,), (yq.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = out * xsc * ysc
        if b is not None:
            out = out + b.astype(jnp.float32)
        if activation in ("gelu", "relu", "silu", "sigmoid", "tanh"):
            out = getattr(jax.nn, activation)(out) \
                if activation != "tanh" else jnp.tanh(out)
        elif activation not in (None, "", "identity"):
            raise ValueError(f"fp8_gemm: unknown activation {activation!r}")
        return out.astype(jnp.dtype(output_dtype))

    return run_op("fp8_gemm", impl, (x, y, x_scale, y_scale, bias), {},
                  differentiable=False)


__all__ += ["quantize_to_fp8", "fp8_gemm"]
