"""Weight initializers (reference: python/paddle/nn/initializer/*).

Each initializer is a callable ``init(shape, dtype) -> jax.Array`` drawing
from the global generator (so ``paddle_tpu.seed`` controls init
reproducibility, matching the reference's per-op seed semantics).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_rng_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


def _fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(next_rng_key(), shape, jnp.float32)
                * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        x = jax.random.truncated_normal(next_rng_key(), self.a, self.b, shape,
                                        jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_rng_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_rng_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(next_rng_key(), shape, jnp.float32)
                * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_rng_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(next_rng_key(), shape, jnp.float32)
                * std).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = jnp.asarray(getattr(self.value, "_value", self.value), dtype)
        if tuple(v.shape) != tuple(shape):
            v = jnp.reshape(v, shape)
        return v


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return self._ortho(shape).astype(dtype)

    def _ortho(self, shape):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (rows, cols)
        a = jax.random.normal(next_rng_key(), flat, jnp.float32)
        q, r = jnp.linalg.qr(a if rows >= cols else a.T)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return self.gain * jnp.reshape(q[:rows, :], shape)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                out[(g * per + i, i) + spatial_center] = 1.0
        return jnp.asarray(out, dtype)


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed convs (reference
    nn/initializer/Bilinear): weights implement bilinear interpolation."""

    def __call__(self, shape, dtype="float32"):
        import numpy as _np
        c_out, c_in, kh, kw = shape
        f = _np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = _np.zeros(shape, _np.float32)
        for i in range(kh):
            for j in range(kw):
                v = (1 - abs(i / f - c)) * (1 - abs(j / f - c))
                w[:, :, i, j] = v
        import jax.numpy as _jnp
        from ..core.dtypes import canonical_dtype
        return _jnp.asarray(w, canonical_dtype(dtype))


_GLOBAL_INITIALIZER = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Reference set_global_initializer: default init for subsequently
    created parameters (create_parameter consults this when no
    default_initializer is given)."""
    _GLOBAL_INITIALIZER["weight"] = weight_init
    _GLOBAL_INITIALIZER["bias"] = bias_init


def get_global_initializer(is_bias=False):
    return _GLOBAL_INITIALIZER["bias" if is_bias else "weight"]
