"""Gradient clipping (reference: python/paddle/nn/clip.py —
``ClipGradByGlobalNorm`` used by every hybrid-parallel optimizer; the
distributed variant reduces the global norm across mp/pp/sharding groups,
hybrid_parallel_optimizer.py:255)."""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grads_"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, Tensor]]):
        raise NotImplementedError

    def apply_values(self, grads: dict) -> dict:
        """Pure functional variant over {name: grad array} — used inside
        jitted train steps."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out

    def apply_values(self, grads):
        return {k: jnp.clip(v, self.min, self.max) for k, v in grads.items()}


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, g):
        n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def __call__(self, params_grads):
        return [(p, Tensor(self._clip(g._value)) if g is not None else g)
                for p, g in params_grads]

    def apply_values(self, grads):
        return {k: self._clip(v) for k, v in grads.items()}


class ClipGradByGlobalNorm(ClipGradBase):
    """Scale all grads by clip_norm/global_norm.  ``group_norm_fn`` lets the
    hybrid-parallel optimizer inject a cross-group reduction of the squared
    norm (the jit path does this with a psum over mesh axes)."""

    def __init__(self, clip_norm=1.0, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_norm_fn = None

    def _global_norm_sq(self, values):
        total = None
        for g in values:
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            total = s if total is None else total + s
        if total is None:
            total = jnp.zeros((), jnp.float32)
        if self.group_norm_fn is not None:
            total = self.group_norm_fn(total)
        return total

    def __call__(self, params_grads):
        gs = [g._value for _, g in params_grads if g is not None]
        total = self._global_norm_sq(gs)
        gn = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value.astype(jnp.float32)
                                       * scale).astype(g.dtype))))
        return out

    def apply_values(self, grads):
        total = self._global_norm_sq(list(grads.values()))
        gn = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return {k: (v.astype(jnp.float32) * scale).astype(v.dtype)
                for k, v in grads.items()}


def clip_grads_(parameters, clip) -> None:
    pgs = [(p, p.grad) for p in parameters if p.grad is not None]
    for (p, _), (_, g) in zip(pgs, clip(pgs)):
        p.grad = g
